"""Chunked, resumable recovery with reservation throttling
(reference: ObjectRecoveryProgress / get_recovery_chunk_size,
src/osd/ECBackend.cc:590-620; src/common/AsyncReserver.h)."""

import threading
import time

import numpy as np
import pytest

from ceph_tpu.core.context import Context
from ceph_tpu.core.reserver import AsyncReserver
from ceph_tpu.crush import map as cmap
from ceph_tpu.ec import codec_from_profile
from ceph_tpu.osd import messages as m
from ceph_tpu.osd import types as t_
from ceph_tpu.osd.daemon import OSDService
from ceph_tpu.osd.osdmap import OSDMap, PGPool, POOL_REPLICATED
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import Collection, GHObject

from test_osd_cluster import LibClient

N_OSDS = 3
POOL = 1
CHUNK = 4096


def build_map():
    cm, root = cmap.build_flat_cluster(N_OSDS, hosts=N_OSDS)
    cm.add_simple_rule("replicated", root, 1, mode="firstn")
    osdmap = OSDMap(cm, max_osd=N_OSDS)
    osdmap.add_pool(PGPool(POOL, POOL_REPLICATED, size=2, min_size=1,
                           pg_num=4, pgp_num=4, crush_rule=0))
    return osdmap


class SmallChunkCluster:
    """Mini cluster with a tiny recovery chunk so objects need many
    push chunks."""

    def __init__(self) -> None:
        self.ctx = Context("osd.rcluster", {
            "osd_recovery_chunk_size": CHUNK,
            "osd_recovery_max_active": 1,
        })
        self.osdmap = build_map()
        self.osds = {}
        self.watchers = []
        for i in range(N_OSDS):
            svc = OSDService(self.ctx, i, MemStore(), self.osdmap,
                             codec_from_profile)
            svc.store.mkfs()
            svc.init()
            self.osds[i] = svc
        self.refresh()
        self.activate()

    refresh = __import__("test_osd_cluster").MiniCluster.refresh
    activate = __import__("test_osd_cluster").MiniCluster.activate
    kill = __import__("test_osd_cluster").MiniCluster.kill
    revive = __import__("test_osd_cluster").MiniCluster.revive
    shutdown = __import__("test_osd_cluster").MiniCluster.shutdown
    primary_of = __import__("test_osd_cluster").MiniCluster.primary_of


@pytest.fixture()
def cluster():
    c = SmallChunkCluster()
    yield c
    c.shutdown()


@pytest.fixture()
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


def test_chunked_push_and_resume(cluster, client):
    """Interrupt a multi-chunk recovery push mid-object; the retry
    resumes from persisted progress instead of byte 0."""
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=10 * CHUNK, dtype=np.uint8).tobytes()
    client.put(POOL, "big", data)
    pgid, acting, primary = cluster.primary_of(POOL, "big")
    victim = next(o for o in acting if o != primary)

    cluster.kill(victim)
    data2 = rng.integers(0, 256, size=10 * CHUNK,
                         dtype=np.uint8).tobytes()
    client.put(POOL, "big", data2)  # degraded write: victim lags

    # interrupt: let only the first 3 pushes through, then drop the rest
    pg = cluster.osds[primary].pgs[pgid]
    osd = cluster.osds[primary]
    orig_rpc = osd.rpc
    pushed = {"n": 0, "bytes": 0}

    def flaky_rpc(peers_msgs, timeout=10.0):
        kept = []
        for osd_id, msg in peers_msgs:
            if isinstance(msg, m.MPGPush) and not msg.deleted:
                if pushed["n"] >= 3:
                    continue  # dropped: peer "died" mid-recovery
                pushed["n"] += 1
                pushed["bytes"] += len(msg.data)
            kept.append((osd_id, msg))
        return orig_rpc(kept, timeout=min(timeout, 3.0)) if kept else []

    osd.rpc = flaky_rpc
    try:
        cluster.revive(victim)  # recovery starts, gets interrupted
        time.sleep(0.5)
    finally:
        osd.rpc = orig_rpc

    # the victim persisted partial progress
    coll = Collection(t_.pgid_str(pgid) + "_head")
    vstore = cluster.osds[victim].store
    blob = vstore.getattr(coll, GHObject("big"), "_rprogress")
    assert blob, "no persisted recovery progress"
    # victim still counts the object content as not-authoritative
    assert vstore.read(coll, GHObject("big")) != data2

    # retry with a byte spy: the resumed push must NOT restart at 0
    resumed = {"offs": [], "bytes": 0}

    def spy_rpc(peers_msgs, timeout=10.0):
        for osd_id, msg in peers_msgs:
            if isinstance(msg, m.MPGPush) and not msg.deleted:
                resumed["offs"].append(msg.off)
                resumed["bytes"] += len(msg.data)
        return orig_rpc(peers_msgs, timeout)

    osd = cluster.osds[primary]
    osd.rpc = spy_rpc
    try:
        cluster.refresh()
        cluster.activate()
        deadline = time.time() + 15
        while time.time() < deadline:
            if vstore.read(coll, GHObject("big")) == data2:
                break
            time.sleep(0.2)
    finally:
        osd.rpc = spy_rpc  # leave spy; cluster torn down after
    assert vstore.read(coll, GHObject("big")) == data2
    push_offs = [o for o in resumed["offs"]]
    assert push_offs and min(push_offs) > 0, (
        f"resume restarted from 0 (offs={push_offs[:5]})"
    )
    assert resumed["bytes"] < len(data2), "resume re-sent the whole object"
    # progress marker cleared after completion
    try:
        left = vstore.getattr(coll, GHObject("big"), "_rprogress")
    except Exception:
        left = None
    assert not left


def test_reserver_bounds_concurrency():
    r = AsyncReserver(2)
    running = []
    peak = []
    lock = threading.Lock()

    def worker():
        with r:
            with lock:
                running.append(1)
                peak.append(len(running))
            time.sleep(0.05)
            with lock:
                running.pop()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) <= 2
    assert r.high_water <= 2
    assert r.in_use == 0


def test_reserver_timeout():
    r = AsyncReserver(1)
    assert r.reserve()
    assert not r.reserve(timeout=0.1)
    r.release()
    assert r.reserve(timeout=0.1)
