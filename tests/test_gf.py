"""GF(2^w) field math: numpy reference vs native oracle vs algebraic laws."""

import numpy as np
import pytest

from ceph_tpu import _native
from ceph_tpu.ec import gf, matrices


def test_gf256_tables_match_native():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, size=2048).astype(np.uint32)
    b = rng.integers(0, 256, size=2048).astype(np.uint32)
    ours = gf.mul(a, b, 8)
    theirs = np.array([_native.gf256_mul(int(x), int(y)) for x, y in zip(a, b)])
    np.testing.assert_array_equal(ours, theirs)


def test_gf256_inverse():
    a = np.arange(1, 256, dtype=np.uint32)
    assert np.all(gf.mul(a, gf.inv(a, 8), 8) == 1)
    for x in range(1, 256):
        assert _native.gf256_inv(x) == int(gf.inv(x, 8))


@pytest.mark.parametrize("w", [4, 8, 16])
def test_field_laws(w):
    rng = np.random.default_rng(w)
    n = 1 << w
    a = rng.integers(0, n, size=256).astype(np.uint32)
    b = rng.integers(0, n, size=256).astype(np.uint32)
    c = rng.integers(0, n, size=256).astype(np.uint32)
    assert np.all(gf.mul(a, b, w) == gf.mul(b, a, w))
    assert np.all(
        gf.mul(a, b ^ c, w) == (gf.mul(a, b, w) ^ gf.mul(a, c, w))
    )
    assert np.all(gf.mul(gf.mul(a, b, w), c, w) == gf.mul(a, gf.mul(b, c, w), w))


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(1)
    for k in (2, 4, 8):
        M = matrices.full_generator(matrices.isa_cauchy(k, 3))[: k + 3]
        sub = M[rng.permutation(k + 3)[:k]]
        inv = gf.mat_inv(sub, 8)
        assert np.array_equal(gf.matmul(inv, sub, 8), np.eye(k, dtype=np.uint32))


def test_native_mat_invert_agrees():
    rng = np.random.default_rng(2)
    k = 8
    M = matrices.full_generator(matrices.isa_rs_vandermonde(k, 4))
    rows = np.sort(rng.permutation(k + 4)[:k])
    sub = np.ascontiguousarray(M[rows], dtype=np.uint8)
    out = np.zeros((k, k), dtype=np.uint8)
    rc = _native.lib().gf256_mat_invert(_native._u8(sub), _native._u8(out), k)
    assert rc == 0
    np.testing.assert_array_equal(out, gf.mat_inv(M[rows], 8).astype(np.uint8))


def test_bitmatrix_is_multiplication():
    rng = np.random.default_rng(3)
    for c in [0, 1, 2, 3, 0x1D, 0xFF, 0x80]:
        B = gf.const_to_bitmatrix(c, 8)
        x = rng.integers(0, 256, size=64).astype(np.uint8)
        xbits = gf.bytes_to_bitplanes(x[None, :])
        ybits = (B.astype(np.uint32) @ xbits.astype(np.uint32)) % 2
        y = gf.bitplanes_to_bytes(ybits.astype(np.uint8))[0]
        np.testing.assert_array_equal(y, gf.mul(c, x, 8).astype(np.uint8))


def test_bitplane_roundtrip():
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, size=(3, 5, 32), dtype=np.uint8)
    np.testing.assert_array_equal(
        gf.bitplanes_to_bytes(gf.bytes_to_bitplanes(data)), data
    )
