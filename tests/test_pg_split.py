"""PG split on pg_num growth (reference PG::split_colls /
OSD::split_pgs + OSDMonitor pool set pg_num).

Design under test: with pgp_num unchanged, a child pg folds to its
parent's pps (raw_pg_to_pps stable_mods ps by pgp_num), so children
place on the SAME osds and the split is purely local and
deterministic on every member.
"""

import sys, os

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_osd_cluster import MiniCluster, LibClient, REP_POOL, EC_POOL

from ceph_tpu.osd import map_codec
from ceph_tpu.osd.osdmap import stable_mod


@pytest.fixture
def cluster():
    c = MiniCluster()
    yield c
    c.shutdown()


@pytest.fixture
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


def _grow_pg_num(cluster, pool_id, new_pg_num):
    newmap = map_codec.decode_osdmap(
        map_codec.encode_osdmap(cluster.osdmap))
    newmap.epoch = cluster.osdmap.epoch + 1
    newmap.pools[pool_id].pg_num = new_pg_num  # pgp_num unchanged
    cluster.osdmap = newmap
    cluster.refresh()
    cluster.activate()


def test_children_colocate_with_parent(cluster):
    m = cluster.osdmap
    pool = m.pools[REP_POOL]
    old_n = pool.pg_num
    _grow_pg_num(cluster, REP_POOL, old_n * 2)
    m2 = cluster.osdmap
    for child in range(old_n, old_n * 2):
        parent = stable_mod(child, old_n, pool.pg_num_mask_)
        up_c, _p1, _a1, _ap1 = m2.pg_to_up_acting((REP_POOL, child))
        up_p, _p2, _a2, _ap2 = m2.pg_to_up_acting((REP_POOL, parent))
        assert up_c == up_p, (child, parent)


def test_split_moves_objects_and_serves_io(cluster, client):
    io_names = [f"obj{i}" for i in range(40)]
    for n in io_names:
        client.put(REP_POOL, n, (n * 50).encode())
    old_n = cluster.osdmap.pools[REP_POOL].pg_num
    _grow_pg_num(cluster, REP_POOL, old_n * 2)
    newp = cluster.osdmap.pools[REP_POOL]
    # every object is now resident in the pg its NEW ps names
    moved = 0
    for n in io_names:
        pgid = cluster.osdmap.object_to_pg(REP_POOL, n)
        if pgid[1] >= old_n:
            moved += 1
        _up, _upp, acting, primary = cluster.osdmap.pg_to_up_acting(pgid)
        pg = cluster.osds[primary].pgs[pgid]
        names = pg.backend.object_names()
        assert n in names, f"{n} not resident in its new pg {pgid}"
    assert moved > 0, "doubling pg_num must move some objects"
    # reads and writes keep working through the client after the split
    for n in io_names:
        assert client.get(REP_POOL, n) == (n * 50).encode()
    client.put(REP_POOL, "post-split", b"fresh")
    assert client.get(REP_POOL, "post-split") == b"fresh"


def test_split_ec_pool_moves_all_shards(cluster, client):
    names = [f"ec{i}" for i in range(24)]
    for n in names:
        client.put(EC_POOL, n, (n * 99).encode())
    old_n = cluster.osdmap.pools[EC_POOL].pg_num
    _grow_pg_num(cluster, EC_POOL, old_n * 2)
    for n in names:
        pgid = cluster.osdmap.object_to_pg(EC_POOL, n)
        _up, _upp, acting, _ap = cluster.osdmap.pg_to_up_acting(pgid)
        holders = [o for o in acting if o >= 0]
        for osd_id in holders:
            pg = cluster.osds[osd_id].pgs.get(pgid)
            assert pg is not None
            assert n in pg.backend.object_names(), (n, pgid, osd_id)
        assert client.get(EC_POOL, n) == (n * 99).encode()


def test_pool_set_pg_num_end_to_end():
    """tier-3: `osd pool set pg_num` through the mon -> incremental map
    -> subscription push -> local split on every OSD -> client IO keeps
    working (stale-epoch ops are ESTALE'd and transparently retried)."""
    from ceph_tpu.vstart import VStartCluster

    with VStartCluster(n_mons=1, n_osds=3) as c:
        pool = c.create_pool("grow", size=2, pg_num=4)
        io = c.client().ioctx(pool)
        names = [f"g{i}" for i in range(30)]
        for n in names:
            io.write_full(n, (n * 20).encode())
        code, out = c.command({"prefix": "osd pool set", "pool": "grow",
                               "var": "pg_num", "val": 8})
        assert code == 0 and out["pg_num"] == 8

        def split_done():
            m = c.leader().osdmap
            return m is not None and m.pools[pool].pg_num == 8

        c.wait_for(split_done, what="pg_num growth")
        for n in names:
            assert io.read(n) == (n * 20).encode()
        io.write_full("after", b"ok")
        assert io.read("after") == b"ok"
        assert sorted(io.list_objects()) == sorted(names + ["after"])
        # shrinking is refused
        code, _ = c.command({"prefix": "osd pool set", "pool": "grow",
                             "var": "pg_num", "val": 4})
        assert code == -22


def test_pgp_num_growth_migrates_children():
    """The split follow-on: raising pgp_num un-folds child placement —
    children remap to their own CRUSH positions and (re)peering moves
    the data; client IO survives the whole sequence."""
    from ceph_tpu.vstart import VStartCluster

    with VStartCluster(n_mons=1, n_osds=4) as c:
        pool = c.create_pool("mig", size=2, pg_num=4)
        io = c.client().ioctx(pool)
        names = [f"m{i}" for i in range(24)]
        for n in names:
            io.write_full(n, (n * 31).encode())
        code, _ = c.command({"prefix": "osd pool set", "pool": "mig",
                             "var": "pg_num", "val": 8})
        assert code == 0
        c.wait_for(lambda: c.leader().osdmap.pools[pool].pg_num == 8,
                   what="pg_num growth")
        code, _ = c.command({"prefix": "osd pool set", "pool": "mig",
                             "var": "pgp_num", "val": 8})
        assert code == 0
        c.wait_for(lambda: c.leader().osdmap.pools[pool].pgp_num == 8,
                   what="pgp_num growth")

        def children_replaced():
            m = c.leader().osdmap
            # at least one child now places differently from its parent
            for child in range(4, 8):
                up_c, _1, _2, _3 = m.pg_to_up_acting((pool, child))
                up_p, _4, _5, _6 = m.pg_to_up_acting((pool, child - 4))
                if up_c != up_p:
                    return True
            return False

        assert children_replaced(), "pgp bump should re-place children"
        # every object still readable after migration/peering settles
        deadline_names = list(names)

        def all_readable():
            for n in deadline_names:
                try:
                    if io.read(n) != (n * 31).encode():
                        return False
                except Exception:
                    return False
            return True

        c.wait_for(all_readable, timeout=60.0, what="post-migration reads")
        io.write_full("post-mig", b"ok")
        assert io.read("post-mig") == b"ok"


def test_pgp_num_growth_migrates_ec_children():
    """EC twin of the migration test: displaced EC children rebuild
    their shards by reading from prior-interval holders."""
    from ceph_tpu.vstart import VStartCluster

    with VStartCluster(n_mons=1, n_osds=5) as c:
        pool = c.create_pool(
            "ecmig", size=3, pool_type="erasure", pg_num=4,
            ec_profile="plugin=isa k=2 m=1 technique=reed_sol_van")
        io = c.client().ioctx(pool)
        names = [f"e{i}" for i in range(16)]
        for n in names:
            io.write_full(n, (n * 41).encode())
        for var, val in (("pg_num", 8), ("pgp_num", 8)):
            code, _ = c.command({"prefix": "osd pool set",
                                 "pool": "ecmig", "var": var,
                                 "val": val})
            assert code == 0
        c.wait_for(lambda: c.leader().osdmap.pools[pool].pgp_num == 8,
                   what="pgp growth")

        def all_readable():
            try:
                return all(io.read(n) == (n * 41).encode()
                           for n in names)
            except Exception:
                return False

        c.wait_for(all_readable, timeout=90.0,
                   what="post-migration EC reads")
        io.write_full("ec-post", b"ok")
        assert io.read("ec-post") == b"ok"
