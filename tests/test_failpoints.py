"""Deterministic fault injection: the failpoint registry itself, the
filestore EIO wiring, and — the PR-7 tentpole — the committed
barrier/drop schedule that reproduces the 0xd403 acked-write-vs-
rollback loss class without load or luck.

The 0xd403 class (ROUND6_NOTES.md): under 2x CPU overload, ~1/3 of
thrash replays lost ACKED state (xattr loss, byte divergence, a
missing object), always immediately after a `rolled back 1 divergent
entries` line.  Root cause: a DEGRADED EC commit (a peer died
mid-write, the op completed on k members via drop_missing) acked the
client with the committed_to watermark broadcast fire-and-forget — so
the primary dying inside the broadcast-delivery window (which 2x CPU
load stretches past the thrash kill gap) left the acked entry's
watermark nowhere durable.  The next peering round, with the acting
set remapped whole, counted < k holders for the entry, floored the
authoritative head below it, and rewound acknowledged state.

The schedule here replays that interleaving in milliseconds:
sub-write-to-peer DROPPED (kill-boundary loss) -> peer killed ->
degraded commit -> all commit-note persists DROPPED (the in-flight
notes dying with the primary) -> primary killed -> remap + whole-set
arbitration.  At pre-fix HEAD the client holds an ack for state the
rollback then destroys (this test FAILS); with the durable-ack gate
the client is only acked once a surviving peer persisted the
watermark, so either the ack never happened (EAGAIN, honest) or the
state survives.
"""

import threading
import time

import pytest

import ceph_tpu.core.failpoint as fp
from ceph_tpu.osd import types as t_

from tests.test_osd_cluster import (EC_POOL, LibClient, MiniCluster,
                                    N_OSDS)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.disarm_all()
    yield
    fp.disarm_all()


# ---------------------------------------------------------------------------
# registry unit coverage
# ---------------------------------------------------------------------------


def test_registry_unknown_name_refused():
    with pytest.raises(KeyError):
        fp.arm("pg.totally.bogus", fp.sleep_ms(1))
    with pytest.raises(ValueError):
        fp.arm_from_spec("pg.commit.client_reply=explode")


def test_disarmed_is_noop_and_cheap():
    assert fp.failpoint("pg.commit.client_reply") is None
    assert not fp.enabled("pg.commit.client_reply")
    # zero-overhead acceptance: the disarmed guard is one global load
    # + None check (typical ~0.2µs; the write path crosses O(1) points
    # per ~10ms op).  Min-of-5 batches defeats scheduler noise on a
    # loaded box; the 5µs budget is ~25x the typical cost and still
    # catches any accidental dict/exception machinery on the path.
    n = 20000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            fp.failpoint("pg.commit.client_reply")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 5e-6, f"disarmed failpoint cost {best*1e9:.0f}ns"


def test_modifiers_once_count_prob_match():
    fp.arm("backend.commit.ack", fp.sleep_ms(0), count=2)
    for _ in range(5):
        fp.failpoint("backend.commit.ack")
    assert fp.fired("backend.commit.ack") == 2
    assert not fp.enabled("backend.commit.ack")  # self-disarmed

    fp.arm("pg.rollback.entry", fp.DROP_ACTION, match={"oid": "m2"})
    assert fp.failpoint("pg.rollback.entry", oid="m7") is None
    assert fp.failpoint("pg.rollback.entry", oid="m2") is fp.DROP
    fp.disarm("pg.rollback.entry")

    # seeded prob: same seed => identical firing pattern
    def pattern(seed):
        fp.disarm_all()
        fp.seed(seed)
        fp.arm("pglog.rewind", fp.DROP_ACTION, prob=0.5)
        return [fp.failpoint("pglog.rewind") is fp.DROP
                for _ in range(64)]

    a, b, c = pattern(0xD403), pattern(0xD403), pattern(0x1EC)
    assert a == b
    assert a != c  # different seed, different schedule


def test_error_and_dsl_roundtrip():
    fp.arm_from_spec("store.commit_batch.sync=error(RuntimeError):once")
    with pytest.raises(RuntimeError):
        fp.failpoint("store.commit_batch.sync")
    assert fp.failpoint("store.commit_batch.sync") is None  # once spent


def test_barrier_rendezvous_and_abort():
    fp.arm("queue.batch.dispatch", fp.barrier("hold-batch"))
    hit = []

    def worker():
        try:
            fp.failpoint("queue.batch.dispatch")
            hit.append("through")
        except fp.FailpointAborted:
            hit.append("aborted")

    th = threading.Thread(target=worker, daemon=True)
    th.start()
    assert fp.wait_hit("hold-batch", timeout=5.0)
    assert not hit  # parked, deterministically
    fp.release("hold-batch")
    th.join(5.0)
    assert hit == ["through"]

    fp.arm("queue.batch.dispatch", fp.barrier("hold-batch2"))
    th2 = threading.Thread(target=worker, daemon=True)
    th2.start()
    assert fp.wait_hit("hold-batch2", timeout=5.0)
    fp.abort("hold-batch2")
    th2.join(5.0)
    assert hit == ["through", "aborted"]


# ---------------------------------------------------------------------------
# filestore_debug_inject_read_err wiring (satellite)
# ---------------------------------------------------------------------------


def test_filestore_read_err_injection(tmp_path):
    from ceph_tpu.store.filestore import FileStore
    from ceph_tpu.store.objectstore import (Collection, GHObject,
                                            StoreError, Transaction)

    st = FileStore(str(tmp_path / "fs"))
    st.mkfs()
    st.mount()
    coll, g = Collection("1.0_head"), GHObject("victim")
    t = Transaction()
    t.create_collection(coll)
    t.write(coll, g, 0, b"payload")
    st.queue_transaction(t)
    try:
        # conf off: marking alone injects nothing
        st.debug_inject_read_err(coll, g)
        assert st.read(coll, g) == b"payload"
        # conf on (the previously-orphaned option, wired through the
        # daemon's _apply_fault_conf): marked object reads EIO
        st.debug_read_err_enabled = True
        with pytest.raises(StoreError):
            st.read(coll, g)
        st.debug_clear_read_err()
        assert st.read(coll, g) == b"payload"
        # the generic failpoint route needs no marking at all
        fp.arm_from_spec(
            "store.filestore.read=error(EIO):match(oid=victim)")
        with pytest.raises(StoreError):
            st.read(coll, g)
        fp.disarm("store.filestore.read")
    finally:
        st.umount()


def test_filestore_conf_plumbs_to_store():
    """OSDService.init applies filestore_debug_inject_read_err to its
    store and observes runtime toggles."""
    from ceph_tpu.core.context import Context
    from ceph_tpu.osd.daemon import OSDService

    ctx = Context("osd.fptest",
                  overrides={"filestore_debug_inject_read_err": True})
    svc = OSDService.__new__(OSDService)  # only the conf hook matters

    class _St:
        debug_read_err_enabled = False

    svc.ctx = ctx
    svc.store = _St()
    svc._log = lambda lvl, msg: None
    svc._apply_fault_conf()
    assert svc.store.debug_read_err_enabled is True
    ctx.conf.set_val("filestore_debug_inject_read_err", False)
    assert svc.store.debug_read_err_enabled is False


# ---------------------------------------------------------------------------
# the committed 0xd403 schedule (tentpole regression)
# ---------------------------------------------------------------------------


def _ec_target(c):
    """An oid whose EC pg has three live distinct acting members, with
    the VICTIM chosen as the member that inherits the primaryship when
    the primary dies (so the doomed-write's non-holder later serves
    the superseding write — the 0xd403 geometry)."""
    for i in range(64):
        oid = f"fp{i}"
        pgid, acting, primary = c.primary_of(EC_POOL, oid)
        members = [int(o) for o in acting if 0 <= o < N_OSDS]
        if len(members) != 3 or len(set(members)) != 3:
            continue
        # probe (map-only, restored): who inherits when primary dies?
        c.osdmap.set_osd_down(primary)
        _pg2, _a2, next_primary = c.primary_of(EC_POOL, oid)
        c.osdmap.set_osd_up(primary)
        next_primary = int(next_primary)
        if next_primary == int(primary) or next_primary not in members:
            continue
        victim = next_primary
        witness = [o for o in members
                   if o not in (int(primary), victim)][0]
        return oid, pgid, int(primary), victim, witness
    raise AssertionError("no suitable EC pg geometry found")


def _setxattr_async(cl, oid, name, value, timeout, box):
    def run():
        try:
            rep = cl.op(EC_POOL, oid,
                        [t_.OSDOp(t_.OP_SETXATTR, name=name,
                                  data=value)],
                        timeout=timeout)
            box.append(rep.result == 0)
        except Exception:
            box.append(False)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th


def test_0xd403_acked_xattr_survives_supersede_after_failover():
    """THE regression schedule (fails at pre-fix HEAD, passes with the
    fix).  The 0xd403 interleaving, barrier/drop-scheduled:

    1. setxattr x1 fans out; the sub-write to the VICTIM is dropped
       (kill-boundary loss) and the victim dies -> the op completes
       DEGRADED on k members and acks the client; every in-flight
       commit note dies too (the 2x-load window).
    2. The victim revives (stale: recovery pushes are held, as when
       the next kill beats the push), the primary dies, and the victim
       — the one member that never saw x1 — inherits the primaryship.
    3. The client writes the object FULL.  The new primary cannot
       reconstruct the current generation (1 of k current chunks
       reachable) so the WRITEFULL supersedes — and pre-fix it carried
       the freshest LOCAL shard's meta forward: the victim's stale,
       pre-x1 image.  The ACKED x1 is gone; the model sees
       `m2: xattr x1`, always right after the failover's
       `rolled back 1 divergent entries` housekeeping.

    Post-fix, both doors are closed: the degraded commit's ack is
    gated on a durable watermark witness (here the notes die, so the
    ack is honestly withheld), and a superseding WRITEFULL ranks
    REMOTE acting shards' meta testimony too, so the freshest stamp —
    the witness's x1-bearing image — is what carries forward."""
    c = MiniCluster()
    cl = LibClient(c)
    c.ctx.conf.set_val("osd_client_write_timeout", 1.0)
    c.ctx.conf.set_val("osd_recovery_push_timeout", 2.0)
    try:
        oid, pgid, primary, victim, witness = _ec_target(c)

        io = cl.rc.ioctx(EC_POOL)
        io.write_full(oid, b"base-payload" * 10)
        io.setxattr(oid, "x0", b"acked-before")  # acked, full width

        # recovery pushes held: the thrash race wins because the next
        # kill beats the push; here we pin that ordering
        fp.arm("msg.frame.deliver", fp.DROP_ACTION,
               match={"mtype": "MPGPush"})
        # the kill-boundary sub-write loss: victim never sees x1
        fp.arm("backend.subwrite.fanout", fp.DROP_ACTION,
               match={"peer": str(victim)})
        # every in-flight commit note dies with its window
        fp.arm("pg.commit_note.persist", fp.DROP_ACTION)

        box = []
        th = _setxattr_async(cl, oid, "x1", b"acked-lost?", 4.0, box)
        deadline = time.monotonic() + 5.0
        while (fp.fired("backend.subwrite.fanout") < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert fp.fired("backend.subwrite.fanout") >= 1
        # the kill boundary: victim dies while the op waits on it ->
        # drop_missing completes the op DEGRADED on k members
        c.kill(victim)
        th.join(6.0)
        x1_acked = bool(box and box[0])

        # victim revives stale, then the primary dies: the non-holder
        # inherits the primaryship
        c.revive(victim)
        c.kill(primary)
        _pg2, _a2, new_primary = c.primary_of(EC_POOL, oid)
        assert int(new_primary) == victim
        fp.disarm("pg.commit_note.persist")  # the window is over

        # the superseding WRITEFULL through the stale new primary
        new_data = b"superseding-payload" * 8
        rep = io.operate(
            oid, [t_.OSDOp(t_.OP_WRITEFULL, data=new_data)],
            timeout=15.0)
        assert rep.result == 0

        # THE ORACLE, read while the old primary is still dead — the
        # superseding generation IS the object now.  Pre-fix x1_acked
        # is True and the supersede wiped x1 from the live shards.
        if x1_acked:
            got = io.operate(
                oid, [t_.OSDOp(t_.OP_GETXATTR, name="x1")],
                timeout=15.0)
            assert got.result == 0 and \
                got.ops[0].out_data == b"acked-lost?", (
                    "acked xattr lost to a superseding full-state "
                    "write: the 0xd403 acked-loss class")
        # state acked BEFORE the schedule must survive it regardless
        assert io.getxattr(oid, "x0") == b"acked-before"
        assert io.read(oid).rstrip(b"\0") == new_data

        fp.disarm_all()
        c.revive(primary)
        c.activate()
        # post-heal the rebuilt shard must match its peers: recovery
        # landing with MERGE semantics resurrected the stale
        # generation's attrs onto one shard (ghost x1 on the revived
        # primary while its peers lacked it), serving rewound state as
        # live depending on who answered the read
        metas = []
        for osd in (primary, victim, witness):
            pg = c.osds[osd].pgs.get(pgid)
            if pg is None:
                continue
            for s in range(3):
                attrs, _om = pg.backend.shard_meta(oid, s)
                if attrs:
                    metas.append({k: v for k, v in attrs.items()
                                  if k not in ("hinfo", "_av")})
        assert metas and all(mm == metas[0] for mm in metas), (
            f"shard user-attrs diverged after recovery: {metas}")
    finally:
        fp.disarm_all()
        cl.shutdown()
        c.shutdown()


def test_degraded_commit_acks_only_after_witness_persists():
    """The fix's liveness + mechanism: same degraded commit, notes NOT
    dropped — the client ack arrives (gated, bounded) and the acked
    state then survives the primary's death because the witness
    persisted the watermark before the ack fired."""
    c = MiniCluster()
    cl = LibClient(c)
    c.ctx.conf.set_val("osd_client_write_timeout", 2.0)
    c.ctx.conf.set_val("osd_recovery_push_timeout", 2.0)
    try:
        oid, pgid, primary, victim, witness = _ec_target(c)

        io = cl.rc.ioctx(EC_POOL)
        io.write_full(oid, b"payload-b" * 9)

        fp.arm("msg.frame.deliver", fp.DROP_ACTION,
               match={"mtype": "MPGPush"})
        fp.arm("backend.subwrite.fanout", fp.DROP_ACTION,
               match={"peer": str(victim)})

        box = []
        th = _setxattr_async(cl, oid, "x1", b"gated-ack", 10.0, box)
        deadline = time.monotonic() + 5.0
        while (fp.fired("backend.subwrite.fanout") < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        c.kill(victim)
        th.join(8.0)
        assert box and box[0], (
            "degraded commit never acked: durable-ack gate wedged")

        # witness persisted the watermark before that ack — verify
        wpg = c.osds[witness].pgs[pgid]
        from ceph_tpu.osd.types import EVersion
        assert wpg.info.committed_to > EVersion(), (
            "ack fired without a durable witness")

        c.revive(victim)
        c.kill(primary)
        c.activate()
        fp.disarm_all()
        c.revive(primary)
        c.activate()
        # the acked xattr survived the primary's death
        assert io.getxattr(oid, "x1") == b"gated-ack"
        assert io.read(oid).rstrip(b"\0") == b"payload-b" * 9
    finally:
        fp.disarm_all()
        cl.shutdown()
        c.shutdown()
