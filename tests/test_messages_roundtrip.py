"""Every registered message type must survive an encode/decode round trip.

Guards against the MScrub class of bug: a subclass with payload fields but
no encode_payload/decode_payload silently drops them (the decoded instance
doesn't even have the attributes, so ms_dispatch blows up mid-connection).
Mirrors the reference's dencoder corpus idea at unit scale: mutate every
scalar field, round-trip through the registry dispatch, and require the
re-encoded bytes to be identical (src/tools/ceph-dencoder/,
src/test/encoding/).
"""

import pytest

# importing these modules populates MSG_REGISTRY
import ceph_tpu.cephfs.messages  # noqa: F401
import ceph_tpu.mon.messages  # noqa: F401
import ceph_tpu.osd.messages  # noqa: F401
from ceph_tpu.msg.message import MSG_REGISTRY, EntityName, Message
from ceph_tpu.osd.types import EVersion


def _mutate(msg: Message) -> None:
    """Give every scalar field a non-default value so a dropped field
    changes the wire image (containers stay empty — their codecs are
    covered by per-subsystem tests)."""
    for name, val in list(vars(msg).items()):
        if name == "src":
            msg.src = EntityName("osd", 3)
        elif name == "pgid":
            msg.pgid = (5, 9)
        elif isinstance(val, bool):
            setattr(msg, name, True)
        elif isinstance(val, int):
            setattr(msg, name, 3)  # fits every u8/u32/s32/u64 field
        elif isinstance(val, float):
            setattr(msg, name, 2.5)
        elif isinstance(val, str):
            setattr(msg, name, "t")
        elif isinstance(val, bytes):
            setattr(msg, name, b"\x01\x02")
        elif isinstance(val, EVersion):
            setattr(msg, name, EVersion(2, 9))


@pytest.mark.parametrize(
    "code,cls", sorted(MSG_REGISTRY.items()), ids=lambda v: getattr(v, "__name__", v)
)
def test_roundtrip(code, cls):
    msg = cls()
    _mutate(msg)
    wire = msg.to_bytes()
    back = Message.from_bytes(wire)
    assert type(back) is cls
    # identical re-encode proves no field was dropped or reordered
    assert back.to_bytes() == wire
    # and the mutated scalars actually made it across
    for name, val in vars(msg).items():
        if isinstance(val, (bool, int, float, str, bytes, tuple, EVersion)):
            assert getattr(back, name) == val, f"{cls.__name__}.{name}"
