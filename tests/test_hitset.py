"""HitSet + tier-agent tests (reference src/osd/HitSet.h,
src/osd/TierAgentState.h, PrimaryLogPG hit_set_* / agent_work roles).
"""

import numpy as np
import pytest

from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.osd.hitset import (
    BloomHitSet,
    ExplicitHitSet,
    HitSetHistory,
    TierAgent,
    decode_hitset,
)


def test_bloom_membership_and_fpp():
    hs = BloomHitSet(target_size=2000, fpp=0.01)
    members = [f"obj{i}" for i in range(2000)]
    for n in members:
        hs.insert(n)
    assert all(hs.contains(n) for n in members)
    # false positives on non-members stay near the target fpp
    probes = [f"other{i}" for i in range(4000)]
    fp = int(hs.contains_batch(probes).sum())
    assert fp / len(probes) < 0.05
    assert hs.is_full()


def test_bloom_batch_matches_scalar():
    hs = BloomHitSet(target_size=100)
    for i in range(0, 100, 2):
        hs.insert(f"o{i}")
    names = [f"o{i}" for i in range(100)]
    batch = hs.contains_batch(names)
    scalar = np.array([hs.contains(n) for n in names])
    assert np.array_equal(batch, scalar)


@pytest.mark.parametrize("cls", [BloomHitSet, ExplicitHitSet])
def test_hitset_encode_roundtrip(cls):
    hs = cls(target_size=50)
    for i in range(30):
        hs.insert(f"x{i}")
    e = Encoder()
    hs.encode(e)
    hs2 = decode_hitset(Decoder(e.bytes()))
    assert type(hs2) is cls
    assert all(hs2.contains(f"x{i}") for i in range(30))
    assert hs2.inserts == hs.inserts


def test_history_temperature_and_promote():
    hist = HitSetHistory(count=3)
    for epoch in range(4):  # 4 periods; ring keeps last 3
        hs = ExplicitHitSet()
        for i in range(10):
            if i % (epoch + 1) == 0:
                hs.insert(f"o{i}")
        hist.add(epoch, epoch + 1, hs)
    assert len(hist.archive) == 3
    assert hist.hit_count("o0") == 3  # hot in every kept set
    temps = hist.temperature_batch([f"o{i}" for i in range(10)])
    assert temps[0] == 3
    agent = TierAgent(hist, min_recency_for_promote=2)
    assert agent.should_promote("o0")
    assert not agent.should_promote("o7")


def test_agent_plan_flush_evict_coldest_first():
    hist = HitSetHistory(count=2)
    hot = ExplicitHitSet()
    hot.insert("hot-dirty")
    hot.insert("hot-clean")
    hist.add(0, 1, hot)
    hist.add(1, 2, hot)
    objects = {  # name -> dirty?
        "hot-dirty": True, "cold-dirty": True,
        "hot-clean": False, "cold-clean": False,
    }
    agent = TierAgent(hist, target_dirty_ratio=0.25,
                      target_full_ratio=0.5)
    flush, evict = agent.plan(objects, used_ratio=0.9, dirty_ratio=0.5,
                              max_ops=1)
    assert flush == ["cold-dirty"]   # coldest dirty flushes first
    assert evict == ["cold-clean"]   # coldest clean evicts first
    # below thresholds: agent idles
    flush, evict = agent.plan(objects, used_ratio=0.1, dirty_ratio=0.1)
    assert flush == [] and evict == []


def test_pg_records_and_persists_hitsets(tmp_path):
    """PG-level wiring: hits land in the current set, rotation archives
    into the meta omap, a fresh PG reloads the history."""
    from ceph_tpu.core.context import Context
    from ceph_tpu.osd.osdmap import PGPool
    from ceph_tpu.osd.pg import PG
    from ceph_tpu.store.memstore import MemStore

    class StubOSD:
        whoami = 0

        def __init__(self):
            self.store = MemStore()
            self.store.mount()
            self.ctx = Context("osd.0", {})
            self.log = self.ctx.log

        def epoch(self):
            return 1

        def send_to_osd(self, osd, msg):
            pass

    osd = StubOSD()
    pool = PGPool(pool_id=1, hit_set_count=2, hit_set_target_size=5,
                  hit_set_fpp=0.05)
    pg = PG((1, 0), pool, osd)
    pg.create_onstore()
    pg.acting = [0]
    pg.primary = 0
    for i in range(12):  # 12 hits, target 5 -> >=2 rotations
        pg.record_hit(f"obj{i % 6}")
    assert len(pg.hit_set_history.archive) >= 2
    assert pg.hit_set_history.hit_count("obj0") >= 1

    pg2 = PG((1, 0), pool, osd)
    pg2.load_hit_set_history()
    assert len(pg2.hit_set_history.archive) >= 2
    assert pg2.hit_set_history.hit_count("obj0") >= 1


def test_pool_codec_carries_hit_set_params():
    from ceph_tpu.osd.map_codec import _dec_pool, _enc_pool
    from ceph_tpu.osd.osdmap import PGPool

    p = PGPool(pool_id=7, hit_set_count=4, hit_set_period=1.5,
               hit_set_target_size=777, hit_set_fpp=0.02)
    e = Encoder()
    _enc_pool(e, p)
    p2 = _dec_pool(Decoder(e.bytes()))
    assert p2.hit_set_count == 4
    assert abs(p2.hit_set_period - 1.5) < 1e-3
    assert p2.hit_set_target_size == 777
    assert abs(p2.hit_set_fpp - 0.02) < 1e-6
