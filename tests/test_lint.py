"""cephlint tier-1 gate + per-check unit coverage.

The gate: the repo at HEAD must have ZERO violations beyond the
committed baseline (tools/cephlint_baseline.json).  New debt either
gets fixed, gets an inline `# cephlint: disable=<check> — why`
annotation, or is consciously accepted by regenerating the baseline —
never silently merged.

The unit tests feed each check synthetic modules with one planted bug
and one clean variant: the gate is only as good as the checks'
ability to actually catch the bug classes they claim.
"""

import os
import sys
import time

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, os.path.abspath(TOOLS))

import cephlint  # noqa: E402

from ceph_tpu.analysis import (  # noqa: E402
    ALL_CHECKS,
    SourceFile,
    discover_files,
    load_baseline,
    new_violations,
    run_checks,
)
from ceph_tpu.analysis.checks import CHECKS_BY_NAME  # noqa: E402


# -- the tier-1 gate ---------------------------------------------------------

_SCAN = {}


def _repo_scan():
    """One repo-wide scan shared by the gate tests (the parse cache
    makes re-parses free, but the checks themselves cost ~3s/pass on
    the 2-core CI box — no reason to pay it three times)."""
    if not _SCAN:
        t0 = time.perf_counter()
        files = discover_files()
        violations = run_checks(files, ALL_CHECKS)
        _SCAN.update(files=files, violations=violations,
                     elapsed=time.perf_counter() - t0)
    return _SCAN


def test_repo_has_no_new_violations():
    scan = _repo_scan()
    violations, elapsed = scan["violations"], scan["elapsed"]
    baseline = load_baseline(cephlint.DEFAULT_BASELINE)
    new = new_violations(violations, baseline)
    assert not new, (
        "new cephlint violations (fix them, annotate the line with "
        "'# cephlint: disable=<check> — why', or — for consciously "
        "accepted debt — regenerate the baseline with "
        "`python tools/cephlint.py --write-baseline`):\n" + "\n".join(
            f"  {v.path}:{v.line}: [{v.check}] {v.message}" for v in new))
    # the CI-budget contract: full suite, parse included, well under 30s
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s (budget 30s)"


def test_baseline_never_grows_silently():
    """Every baseline entry must still correspond to a live violation:
    fixed debt leaves stale allowance behind, and stale allowance is
    where a regression hides.  (Regenerate the baseline after fixing.)"""
    live = {}
    for v in _repo_scan()["violations"]:
        live[v.key] = live.get(v.key, 0) + 1
    baseline = load_baseline(cephlint.DEFAULT_BASELINE)
    stale = {k: (n, live.get(k, 0)) for k, n in baseline.items()
             if live.get(k, 0) < n}
    assert not stale, (
        "baseline entries exceed live violations — debt was fixed, "
        "shrink the baseline (`python tools/cephlint.py "
        f"--write-baseline`): {stale}")


def test_cli_json_contract():
    """--json exits 0 at HEAD and emits the machine-readable shape."""
    import contextlib
    import io
    import json

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        # one check keeps this a CLI-contract test, not a third full
        # scan (the gate itself is test_repo_has_no_new_violations)
        rc = cephlint.main(["--json", "--checks", "no-sleep-poll"])
    out = json.loads(buf.getvalue())
    assert rc == 0
    assert out["new"] == []
    assert out["files_scanned"] > 100
    assert out["checks"] == ["no-sleep-poll"]


# -- per-check unit coverage -------------------------------------------------

def _lint(tmp_path, code: str, check: str, rel: str = "ceph_tpu/fake.py"):
    p = tmp_path / "snippet.py"
    p.write_text(code)
    return [v for v in run_checks([SourceFile(str(p), rel)],
                                  [CHECKS_BY_NAME[check]])
            if v.check == check]


def test_named_locks_catches_raw_lock(tmp_path):
    bad = _lint(tmp_path, (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.lk = threading.Lock()\n"
        "        self.r = threading.RLock()\n"), "named-locks")
    assert [v.line for v in bad] == [4, 5]
    ok = _lint(tmp_path, (
        "from ceph_tpu.core.lockdep import make_lock\n"
        "lk = make_lock('x')\n"), "named-locks")
    assert not ok


def test_named_locks_inline_suppression(tmp_path):
    ok = _lint(tmp_path, (
        "import threading\n"
        "# cephlint: disable=named-locks — released cross-thread\n"
        "guard = threading.Lock()\n"), "named-locks")
    assert not ok


def test_no_sleep_poll_flags_only_short_literal_in_loop(tmp_path):
    code = (
        "import time\n"
        "def poll():\n"
        "    while True:\n"
        "        time.sleep(0.02)\n"       # flagged: the 20ms poll
        "def pace():\n"
        "    while True:\n"
        "        time.sleep(30.0)\n"       # ok: deliberate long pacing
        "def configurable(iv):\n"
        "    while True:\n"
        "        time.sleep(iv)\n"         # ok: computed interval
        "def once():\n"
        "    time.sleep(0.02)\n")          # ok: not in a loop
    bad = _lint(tmp_path, code, "no-sleep-poll")
    assert [v.line for v in bad] == [4]


def test_silent_except_flags_broad_pass_only(tmp_path):
    code = (
        "def f(x):\n"
        "    try:\n"
        "        x()\n"
        "    except Exception:\n"          # flagged
        "        pass\n"
        "    try:\n"
        "        x()\n"
        "    except (OSError, RuntimeError):\n"  # ok: narrowed
        "        pass\n"
        "    try:\n"
        "        x()\n"
        "    except Exception as e:\n"     # ok: logged
        "        print(e)\n"
        "    try:\n"
        "        x()\n"
        "    except:\n"                    # flagged: bare
        "        pass\n")
    bad = _lint(tmp_path, code, "silent-except")
    assert [v.line for v in bad] == [4, 16]


def test_codec_symmetry_missing_decode(tmp_path):
    bad = _lint(tmp_path, (
        "class T:\n"
        "    def encode_payload(self, e):\n"
        "        e.u32(self.x)\n"), "codec-symmetry")
    assert len(bad) == 1 and bad[0].detail == "missing-decode"


def test_codec_symmetry_transposed_fields(tmp_path):
    code = (
        "class T:\n"
        "    def encode_payload(self, e):\n"
        "        e.u32(self.a)\n"
        "        e.u32(self.b)\n"
        "    def decode_payload(self, d):\n"
        "        self.b = d.u32()\n"       # transposed vs encode
        "        self.a = d.u32()\n")
    bad = _lint(tmp_path, code, "codec-symmetry")
    assert len(bad) == 1 and bad[0].detail.startswith("order:")
    ok = _lint(tmp_path, code.replace(
        "        self.b = d.u32()\n        self.a = d.u32()\n",
        "        self.a = d.u32()\n        self.b = d.u32()\n"),
        "codec-symmetry")
    assert not ok


def test_codec_symmetry_version_tolerance(tmp_path):
    intolerant = (
        "class T:\n"
        "    VERSION = 2\n"
        "    def encode_payload(self, e):\n"
        "        e.u32(self.a)\n"
        "        e.u32(self.b)\n"
        "    def decode_payload(self, d):\n"
        "        self.a = d.u32()\n"
        "        self.b = d.u32()\n")      # blind v2 read of a v1 blob
    bad = _lint(tmp_path, intolerant, "codec-symmetry")
    assert len(bad) == 1 and bad[0].detail == "no-old-version-tolerance"
    tolerant = intolerant.replace(
        "        self.b = d.u32()\n",
        "        if d.remaining_in_frame():\n"
        "            self.b = d.u32()\n"
        "        else:\n"
        "            self.b = 0\n")
    assert not _lint(tmp_path, tolerant, "codec-symmetry")


def test_codec_symmetry_struct_v_gated_ok(tmp_path):
    """PR 19: a decode_payload keying an optional tail on the sender's
    struct_v (Message.struct_v, set from d.start() by the decode
    harness) is version-tolerant — the sanctioned gate when a message
    carries both a versioned tail and the bare trace tail."""
    ok = _lint(tmp_path, (
        "class T:\n"
        "    VERSION = 2\n"
        "    def encode_payload(self, e):\n"
        "        e.u32(self.a)\n"
        "        e.u32(self.b)\n"
        "    def decode_payload(self, d):\n"
        "        self.a = d.u32()\n"
        "        if self.struct_v >= 2:\n"
        "            self.b = d.u32()\n"
        "        else:\n"
        "            self.b = 0\n"), "codec-symmetry")
    assert not ok


def test_codec_symmetry_start_gated_struct_ok(tmp_path):
    ok = _lint(tmp_path, (
        "class S:\n"
        "    def encode(self, e):\n"
        "        e.start(2, 1)\n"
        "        e.u32(self.a)\n"
        "        e.finish()\n"
        "    @classmethod\n"
        "    def decode(cls, d):\n"
        "        v = d.start(2)\n"
        "        out = cls(a=d.u32())\n"
        "        if v >= 2:\n"
        "            out.b = d.u32()\n"
        "        d.end()\n"
        "        return out\n"), "codec-symmetry")
    assert not ok


def test_blocking_flags_sleep_in_async_def(tmp_path):
    code = (
        "import asyncio, time\n"
        "async def pump():\n"
        "    time.sleep(0.1)\n"            # flagged: sync sleep on loop
        "    await asyncio.sleep(0.1)\n")  # ok: awaited
    bad = _lint(tmp_path, code, "no-blocking-on-loop")
    assert [v.line for v in bad] == [3]


def test_blocking_follows_fast_dispatch_call_graph(tmp_path):
    code = (
        "class D:\n"
        "    def ms_can_fast_dispatch(self, msg):\n"
        "        return True\n"
        "    def ms_dispatch(self, conn, msg):\n"
        "        self._helper()\n"
        "        return True\n"
        "    def _helper(self):\n"
        "        self.lock.acquire()\n"    # flagged via the call graph
        "        self.guard.acquire(blocking=False)\n")  # ok: non-block
    bad = _lint(tmp_path, code, "no-blocking-on-loop")
    assert [v.line for v in bad] == [8]


def test_blocking_ignores_plain_dispatcher(tmp_path):
    ok = _lint(tmp_path, (
        "class D:\n"
        "    def ms_can_fast_dispatch(self, msg):\n"
        "        return False\n"           # slow path only: pool thread
        "    def ms_dispatch(self, conn, msg):\n"
        "        self.lock.acquire()\n"
        "        return True\n"), "no-blocking-on-loop")
    assert not ok


def test_jax_purity_flags_np_and_time_in_traced_fn(tmp_path):
    code = (
        "import jax\n"
        "import numpy as np\n"
        "import time\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    t = time.time()\n"            # flagged
        "    return np.sum(x) + t\n"       # flagged
        "def untraced(x):\n"
        "    return np.sum(x)\n")          # ok: not traced
    bad = _lint(tmp_path, code, "jax-purity")
    assert sorted(v.detail for v in bad) == ["np.sum", "time.time"]


def test_jax_purity_follows_pallas_call_kernel(tmp_path):
    code = (
        "from jax.experimental import pallas as pl\n"
        "import numpy as np\n"
        "def _kern(ref, o_ref):\n"
        "    o_ref[...] = np.dot(ref[...], ref[...])\n"  # flagged
        "def run(x):\n"
        "    return pl.pallas_call(_kern, out_shape=None)(x)\n")
    bad = _lint(tmp_path, code, "jax-purity")
    assert len(bad) == 1 and bad[0].detail == "np.dot"


def test_d2h_flags_materializers_in_fast_dispatch_graph(tmp_path):
    code = (
        "import numpy as np\n"
        "class D:\n"
        "    def ms_can_fast_dispatch(self, msg):\n"
        "        return True\n"
        "    def ms_dispatch(self, conn, msg):\n"
        "        self._helper(msg)\n"
        "        return True\n"
        "    def _helper(self, msg):\n"
        "        a = np.asarray(msg.buf)\n"      # flagged: d2h fetch
        "        b = bytes(msg.buf)\n"           # flagged
        "        c = msg.buf.tolist()\n"         # flagged
        "        n = len(msg.buf)\n")            # ok: metadata
    bad = _lint(tmp_path, code, "no-d2h-on-hot-path")
    assert [v.line for v in bad] == [9, 10, 11]


def test_d2h_follows_stripe_queue_worker(tmp_path):
    # the queue worker root is resolved by module path: write the
    # fixture AS ceph_tpu/tpu/queue.py so the root matches
    code = (
        "import numpy as np\n"
        "class StripeBatchQueue:\n"
        "    def _worker(self):\n"
        "        self._run_batch([])\n"
        "    def _run_batch(self, batch):\n"
        "        return np.asarray(batch)\n")    # flagged via worker
    bad = _lint(tmp_path, code, "no-d2h-on-hot-path",
                rel="ceph_tpu/tpu/queue.py")
    assert [v.line for v in bad] == [6]
    # a plain class's methods are NOT roots
    ok = _lint(tmp_path, (
        "import numpy as np\n"
        "class Other:\n"
        "    def _run_batch(self, batch):\n"
        "        return np.asarray(batch)\n"), "no-d2h-on-hot-path")
    assert not ok


def test_d2h_hard_paths_never_baseline(tmp_path):
    """Violations in the device-path modules are excluded from
    --write-baseline output: debt there can never be accepted."""
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    hard = Violation(check="no-d2h-on-hot-path",
                     path="ceph_tpu/tpu/staging.py", line=1,
                     scope="DeviceBuf.x", detail="bytes()", message="m")
    soft = Violation(check="no-d2h-on-hot-path",
                     path="ceph_tpu/osd/backend.py", line=1,
                     scope="ECBackend.x", detail="bytes()", message="m")
    entries = violations_to_baseline([hard, soft])["entries"]
    assert soft.key in entries and hard.key not in entries


def test_parse_error_is_a_violation(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    vs = run_checks([SourceFile(str(p), "ceph_tpu/broken.py")], ALL_CHECKS)
    assert len(vs) == 1 and vs[0].check == "parse-error"


def test_baseline_allows_exact_count_only(tmp_path):
    code = ("import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n")
    p = tmp_path / "m.py"
    p.write_text(code)
    vs = run_checks([SourceFile(str(p), "ceph_tpu/m.py")],
                    [CHECKS_BY_NAME["named-locks"]])
    assert len(vs) == 2
    key = vs[0].key
    assert not new_violations(vs, {key: 2})      # both baselined
    over = new_violations(vs, {key: 1})          # one new beyond debt
    assert len(over) == 1 and over[0].line == 3  # newest-looking first


def test_failpoint_names_flag_typo_and_dynamic(tmp_path):
    bad = _lint(tmp_path, (
        "from ceph_tpu.core import failpoint as fp\n"
        "def f():\n"
        "    fp.failpoint('pg.commit.client_repyl')\n"  # typo'd
    ), "failpoint-name-registry")
    assert len(bad) == 1 and "typo" in bad[0].message

    dyn = _lint(tmp_path, (
        "from ceph_tpu.core import failpoint as fp\n"
        "def f(name):\n"
        "    fp.failpoint(name)\n"
    ), "failpoint-name-registry")
    assert len(dyn) == 1 and "dynamic" in dyn[0].detail

    ok = _lint(tmp_path, (
        "from ceph_tpu.core import failpoint as fp\n"
        "def f():\n"
        "    fp.failpoint('pg.commit.client_reply')\n"
        "    if fp.enabled('msg.frame.deliver'):\n"
        "        fp.failpoint('msg.frame.deliver')\n"
    ), "failpoint-name-registry")
    assert not ok

    # bare Event.wait()-style calls must not false-positive
    clean = _lint(tmp_path, (
        "def f(ev):\n"
        "    ev.enabled('whatever')\n"
        "    arm = None\n"
    ), "failpoint-name-registry")
    assert not clean


def test_span_discipline_unfinished_span(tmp_path):
    bad = _lint(tmp_path, (
        "def f(tr):\n"
        "    s = tr.start_span('x')\n"
        "    s.annotate('commit')\n"  # never finished
    ), "span-discipline")
    assert any("finish" in v.message for v in bad)

    # a bare call nothing can ever finish
    bare = _lint(tmp_path, (
        "def f(tr):\n"
        "    tr.start_span('x')\n"
    ), "span-discipline")
    assert any(v.detail == "start_span-unfinished" for v in bare)

    ok = _lint(tmp_path, (
        "def f(tr):\n"
        "    with tr.start_span('x') as s:\n"
        "        s.annotate('commit')\n"
        "def g(tr):\n"
        "    s = tr.start_span('y')\n"
        "    def cb():\n"
        "        s.finish()\n"  # closure finish counts
        "    return cb\n"
        "def h(tr, op):\n"
        "    op.span = tr.start_span('z')\n"
        "def h2(op):\n"
        "    op.span.finish()\n"  # sibling-method finish (module-wide)
    ), "span-discipline")
    assert not [v for v in ok if v.detail == "start_span-unfinished"]


def test_span_discipline_stage_registry(tmp_path):
    bad = _lint(tmp_path, (
        "def f(top):\n"
        "    top.mark_event('comit_sent')\n"  # typo'd stage
    ), "span-discipline")
    assert len(bad) == 1 and "not declared" in bad[0].message

    dyn = _lint(tmp_path, (
        "def f(top, name):\n"
        "    top.mark_event(name)\n"
    ), "span-discipline")
    assert len(dyn) == 1 and "<dynamic>" in dyn[0].detail

    # literal annotate must be a stage; f-string detail is free-form
    lit = _lint(tmp_path, (
        "def f(span, r):\n"
        "    span.annotate('not_a_stage')\n"
        "    span.annotate(f'reply result={r}')\n"
    ), "span-discipline")
    assert len(lit) == 1 and "not_a_stage" in lit[0].detail

    ok = _lint(tmp_path, (
        "def f(top, self, msg):\n"
        "    top.mark_event('commit_sent')\n"
        "    self._op_stage(msg, 'admitted')\n"
    ), "span-discipline")
    assert not ok


def test_span_discipline_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="span-discipline",
                  path="ceph_tpu/osd/pg.py", line=1,
                  scope="PG.x", detail="start_span-unfinished",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_no_unwatched_jit_flags_every_raw_spelling(tmp_path):
    code = (
        "import functools\n"
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "f = jax.jit(lambda x: x)\n"                    # call
        "@jax.jit\n"                                    # decorator
        "def g(x):\n"
        "    return x\n"
        "h = functools.partial(jax.jit, static_argnames=('n',))\n"
        "def k(kern):\n"
        "    return pl.pallas_call(kern, out_shape=None)\n")
    bad = _lint(tmp_path, code, "no-unwatched-jit")
    assert [v.line for v in bad] == [4, 5, 8, 10]
    # importing the raw entry point by name is flagged too
    imp = _lint(tmp_path, (
        "from jax import jit\n"
        "from jax.experimental.pallas import pallas_call\n"),
        "no-unwatched-jit")
    assert [v.line for v in imp] == [1, 2]
    # the devwatch wrappers are the sanctioned spelling
    ok = _lint(tmp_path, (
        "from ceph_tpu.tpu.devwatch import instrumented_jit\n"
        "f = instrumented_jit(lambda x: x, family='fam')\n"),
        "no-unwatched-jit")
    assert not ok
    # devwatch itself is exempt (it owns the raw entry points)
    exempt = _lint(tmp_path, (
        "import jax\n"
        "f = jax.jit(lambda x: x)\n"), "no-unwatched-jit",
        rel="ceph_tpu/tpu/devwatch.py")
    assert not exempt


def test_no_unwatched_jit_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="no-unwatched-jit",
                  path="ceph_tpu/ops/newkernel.py", line=1,
                  scope="f", detail="jax.jit", message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_jax_purity_follows_instrumented_jit(tmp_path):
    """The devwatch wrappers are trace entry points for purity
    analysis too — converting jax.jit -> instrumented_jit must not
    blind the jax-purity check."""
    code = (
        "import numpy as np\n"
        "from ceph_tpu.tpu.devwatch import instrumented_jit\n"
        "def kernel(x):\n"
        "    return np.sum(x)\n"               # flagged: np in traced fn
        "f = instrumented_jit(kernel, family='fam')\n")
    bad = _lint(tmp_path, code, "jax-purity")
    assert len(bad) == 1 and bad[0].detail == "np.sum"


def test_qos_class_registry_flags_typo(tmp_path):
    bad = _lint(tmp_path, (
        "def f(wq, pgid, run):\n"
        "    wq.queue(pgid, run, qos_class='recvery')\n"  # typo'd
    ), "qos-class-registry")
    assert len(bad) == 1 and "best_effort" in bad[0].message

    ok = _lint(tmp_path, (
        "def f(wq, pgid, run, qcls):\n"
        "    wq.queue(pgid, run, qos_class='recovery')\n"
        "    wq.queue(pgid, run, qos_class='snaptrim')\n"
        "    wq.queue(pgid, run, qos_class=qcls)\n"  # classify_op path
    ), "qos-class-registry")
    assert not ok


def test_qos_class_registry_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="qos-class-registry",
                  path="ceph_tpu/osd/daemon.py", line=1,
                  scope="OSDService.x", detail="qos_class='typo'",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_failpoint_names_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="failpoint-name-registry",
                  path="ceph_tpu/osd/pg.py", line=1,
                  scope="PG.x", detail="failpoint('typo')", message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_no_unverified_read_flags_every_bypass_shape(tmp_path):
    code = (
        "from ceph_tpu.store.objectstore import ObjectStore\n"
        "class MyStore(ObjectStore):\n"
        "    def read(self, cid, oid, off=0, length=0):\n"  # flagged:
        "        pass\n"                       # shadows the verify gate
        "    def _read_span(self, cid, oid, off, length):\n"  # ok: the
        "        pass\n"                       # sanctioned backend hook
        "def peek(store, cid, oid):\n"
        "    return store._read_span(cid, oid, 0, 0)\n"  # flagged: raw
        "def disable(store):\n"
        "    store.verify_reads = False\n"     # flagged: hard-disable
        "def conf_gate(store, ctx):\n"
        "    store.verify_reads = bool(ctx)\n"  # ok: runtime-computed
        "class Bystander:\n"
        "    def read(self):\n"                # ok: not an ObjectStore
        "        pass\n")
    bad = _lint(tmp_path, code, "no-unverified-read")
    assert [v.line for v in bad] == [3, 8, 10]


def test_no_unverified_read_allows_the_gate_itself(tmp_path):
    ok = _lint(tmp_path, (
        "class ObjectStore:\n"
        "    def read(self, cid, oid, off=0, length=0):\n"
        "        data, size, seals = self._read_span(cid, oid, 0, 0)\n"
        "        return data\n"),
        "no-unverified-read", rel="ceph_tpu/store/objectstore.py")
    assert not ok


def test_no_unverified_read_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="no-unverified-read",
                  path="ceph_tpu/osd/backend.py", line=1,
                  scope="ECBackend.x", detail="_read_span(...)",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


# -- shape-bucket-discipline (PR 17) ------------------------------------


def test_shape_bucket_flags_undeclared_family(tmp_path):
    bad = _lint(tmp_path, (
        "from ceph_tpu.tpu.devwatch import instrumented_jit\n"
        "import functools\n"
        "f = instrumented_jit(lambda x: x, family='mystery_kernel')\n"
        "@functools.partial(instrumented_jit, family='other_rogue')\n"
        "def g(x):\n"
        "    return x\n"), "shape-bucket-discipline")
    assert sorted(v.detail for v in bad) == [
        "undeclared-family:mystery_kernel",
        "undeclared-family:other_rogue"]


def test_shape_bucket_allows_declared_families(tmp_path):
    ok = _lint(tmp_path, (
        "from ceph_tpu.tpu.devwatch import instrumented_jit\n"
        "f = instrumented_jit(lambda x: x, family='gf256_swar')\n"
        "g = instrumented_jit(lambda x: x, family='crush_mapper')\n"),
        "shape-bucket-discipline")
    assert not ok


def test_shape_bucket_flags_unpadded_queue_dispatch(tmp_path):
    code = (
        "def dispatch(codec, stacked):\n"
        "    return codec.encode_array(stacked)\n"
        "def padded(codec, stacked, covering):\n"
        "    w = covering(stacked.shape[1])\n"
        "    return codec.encode_array(stacked)\n")
    bad = _lint(tmp_path, code, "shape-bucket-discipline",
                rel="ceph_tpu/tpu/queue.py")
    assert [v.detail for v in bad] == ["unpadded-dispatch:encode_array"]
    # the same code outside the coalescer is not this check's business
    assert not _lint(tmp_path, code, "shape-bucket-discipline",
                     rel="ceph_tpu/osd/other.py")


def test_shape_bucket_flags_unpadded_clay_dispatch(tmp_path):
    """PR 19: the clay array-codec kernels (repair_planes /
    decode_planes) are dispatch tails too — an unpadded coupled-layer
    batch is the same fresh-compile-per-width hazard as the flat
    matmul."""
    code = (
        "def dispatch_array(codec, stacked):\n"
        "    out = codec.repair_planes(0, [1, 2], stacked)\n"
        "    return codec.decode_planes([1, 2, 3], stacked)\n"
        "def padded(codec, stacked, covering):\n"
        "    w = covering(stacked.shape[2], 1)\n"
        "    return codec.repair_planes(0, [1, 2], stacked)\n")
    bad = _lint(tmp_path, code, "shape-bucket-discipline",
                rel="ceph_tpu/tpu/queue.py")
    assert sorted(v.detail for v in bad) == [
        "unpadded-dispatch:decode_planes",
        "unpadded-dispatch:repair_planes"]


def test_shape_bucket_gf256_clay_family_declared():
    """The clay kernel family registered by gf256_swar must be in the
    declared bucket set — otherwise every crep/cdec compile counts as
    a rogue and the steady guard can never arm on a clay pool."""
    from ceph_tpu.tpu import shapebucket

    assert "gf256_clay" in set(shapebucket.declared_families())


def test_shape_bucket_never_baseline(tmp_path):
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="shape-bucket-discipline",
                  path="ceph_tpu/tpu/queue.py", line=1,
                  scope="dispatch", detail="unpadded-dispatch:encode_array",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_shape_bucket_clean_on_repo_tree():
    """The real tree must carry zero violations: every registration
    site's family is declared and every coalescer dispatch pads."""
    from ceph_tpu.analysis.framework import discover_files, run_checks
    from ceph_tpu.analysis.checks import CHECKS_BY_NAME as _BY_NAME

    files = [f for f in discover_files(subdirs=("ceph_tpu",))]
    vs = run_checks(files, [_BY_NAME["shape-bucket-discipline"]])
    assert not vs, [v.message for v in vs]


# -- lane-capability (PR 18) --------------------------------------------


def test_lane_capability_flags_pg_lock_from_fast_dispatch(tmp_path):
    code = (
        "class Svc:\n"
        "    def ms_can_fast_dispatch(self, m):\n"
        "        return True\n"
        "    def ms_dispatch(self, m, pg):\n"
        "        self._apply(pg)\n"
        "    def _apply(self, pg):\n"
        "        with pg.lock:\n"
        "            pass\n")
    bad = _lint(tmp_path, code, "lane-capability")
    assert len(bad) == 1
    v = bad[0]
    assert v.line == 7 and v.detail.startswith("loop:may-take-pg-lock")
    # the message names the propagation chain, not just the site
    assert "ms_dispatch" in v.message
    # a try-acquire cannot deadlock the lane: exempt
    ok = _lint(tmp_path, code.replace(
        "with pg.lock:\n            pass",
        "pg.lock.acquire(blocking=False)"), "lane-capability")
    assert not ok


def test_lane_capability_flags_compile_on_loop(tmp_path):
    bad = _lint(tmp_path, (
        "import jax\n"
        "async def handle(fn):\n"
        "    return jax.jit(fn)\n"), "lane-capability")
    assert [v.detail for v in bad] == ["loop:may-compile:jax.jit()"]
    # the same compile from a plain thread target is fine
    ok = _lint(tmp_path, (
        "import jax\n"
        "import threading\n"
        "def warm(fn):\n"
        "    return jax.jit(fn)\n"
        "def boot(fn):\n"
        "    threading.Thread(target=warm).start()\n"), "lane-capability")
    assert not ok


def test_lane_capability_never_baseline():
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="lane-capability", path="ceph_tpu/osd/osd.py",
                  line=1, scope="Svc._apply",
                  detail="loop:may-take-pg-lock:with pg.lock",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


# -- lock-order-cycle (PR 18) -------------------------------------------


_CYCLE_MODULE = (
    "from ceph_tpu.core.lockdep import make_lock\n"
    "class S:\n"
    "    def __init__(self):\n"
    "        self.a = make_lock('A')\n"
    "        self.b = make_lock('B')\n"
    "        self.c = make_lock('C')\n"
    "    def ab(self):\n"
    "        with self.a:\n"
    "            with self.b:\n"
    "                pass\n"
    "    def bc(self):\n"
    "        with self.b:\n"
    "            with self.c:\n"
    "                pass\n"
    "    def ca(self):\n"
    "        with self.c:\n"
    "            with self.a:\n"
    "                pass\n")


def test_lock_cycle_flags_three_lock_cycle(tmp_path):
    bad = _lint(tmp_path, _CYCLE_MODULE, "lock-order-cycle")
    assert len(bad) == 1
    assert bad[0].detail.startswith("cycle:")
    for name in ("A", "B", "C"):
        assert name in bad[0].detail
    # breaking one edge (ca takes them in the global order) is clean
    ok = _lint(tmp_path, _CYCLE_MODULE.replace(
        "        with self.c:\n            with self.a:",
        "        with self.a:\n            with self.c:"),
        "lock-order-cycle")
    assert not ok


def test_lock_cycle_never_baseline():
    from ceph_tpu.analysis.framework import (Violation,
                                             violations_to_baseline)

    v = Violation(check="lock-order-cycle", path="ceph_tpu/osd/pg.py",
                  line=0, scope="<lock-graph>", detail="cycle:A->B->A",
                  message="m")
    assert v.key not in violations_to_baseline([v])["entries"]


def test_lock_graph_dump_round_trip(tmp_path):
    import json

    from ceph_tpu.analysis.checks.lock_cycle import LockModel

    p = tmp_path / "mod.py"
    p.write_text(_CYCLE_MODULE)
    model = LockModel.of([SourceFile(str(p), "ceph_tpu/mod.py")])
    data = json.loads(json.dumps(model.to_json()))
    assert data["edges"]["A"].keys() == {"B"}
    assert data["cycles"] and sorted(data["cycles"][0][:-1]) == \
        ["A", "B", "C"]
    dot = model.to_dot()
    assert '"A" -> "B"' in dot
    # cycle edges are highlighted for the graphviz eye
    assert "[color=red]" in dot


# -- unguarded-shared-state (PR 18) -------------------------------------


def test_shared_state_flags_cross_role_unguarded_read(tmp_path):
    code = (
        "import threading\n"
        "from ceph_tpu.core.lockdep import make_lock\n"
        "class Stats:\n"
        "    def __init__(self):\n"
        "        self._lock = make_lock('stats')\n"
        "        self._count = 0\n"
        "        threading.Thread(target=self._tick_loop).start()\n"
        "    def _tick_loop(self):\n"
        "        with self._lock:\n"
        "            self._count += 1\n"
        "    async def handle(self):\n"
        "        return self._count\n")
    bad = _lint(tmp_path, code, "unguarded-shared-state")
    assert [(v.scope, v.detail) for v in bad] == [("Stats", "_count")]
    assert "handle" in bad[0].message and "_tick_loop" in bad[0].message
    # the guarded read variant is clean
    ok = _lint(tmp_path, code.replace(
        "        return self._count",
        "        with self._lock:\n"
        "            return self._count"), "unguarded-shared-state")
    assert not ok


def test_shared_state_same_lane_is_sequential(tmp_path):
    # writer and reader on the SAME lane: no race, no violation
    ok = _lint(tmp_path, (
        "from ceph_tpu.core.lockdep import make_lock\n"
        "class Seq:\n"
        "    def __init__(self):\n"
        "        self._lock = make_lock('seq')\n"
        "        self._n = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def peek(self):\n"
        "        return self._n\n"), "unguarded-shared-state")
    assert not ok


# -- CLI: --changed / --write-baseline / --lock-graph (PR 18) -----------


def test_cli_changed_scopes_reporting():
    import contextlib
    import io
    import json

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cephlint.main(["--json", "--changed",
                            "--checks", "no-sleep-poll"])
    out = json.loads(buf.getvalue())
    assert rc == 0
    assert out["changed_vs"] == "HEAD"
    assert out["new"] == []


def test_cli_write_baseline_prunes_stale_keys(tmp_path):
    import contextlib
    import io
    import json

    stale = "no-sleep-poll::ceph_tpu/gone.py::nobody::deleted"
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        {"comment": "test", "entries": {stale: 3}}))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cephlint.main(["--write-baseline", "--baseline", str(bl),
                            "--checks", "no-sleep-poll"])
    out = buf.getvalue()
    assert rc == 0
    assert f"- {stale}" in out, out
    rewritten = json.loads(bl.read_text())["entries"]
    assert stale not in rewritten


def test_cli_lock_graph_json():
    import contextlib
    import io
    import json

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cephlint.main(["--lock-graph", "json"])
    out = json.loads(buf.getvalue())
    assert rc == 0
    assert out["cycles"] == [], out["cycles"]
    # the real tree's graph is non-trivial: the PG lock orders ahead
    # of per-subsystem locks
    assert out["edges"], "static graph is empty"
