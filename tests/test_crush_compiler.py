"""CRUSH text compiler/decompiler tests (reference:
src/crush/CrushCompiler.cc; the `crushtool -c / -d` round-trip the
reference's own test_crushtool.sh exercises).
"""

import numpy as np
import pytest

from ceph_tpu.crush import map as cmap
from ceph_tpu.crush import mapper
from ceph_tpu.crush.compiler import CompileError, compile_text, decompile

TEXT = """
# begin crush map
tunable choose_local_tries 0
tunable choose_total_tries 50
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3

# types
type 0 osd
type 1 host
type 10 root

# buckets
host host-a {
    id -1
    alg straw2
    hash 0  # rjenkins1
    item osd.0 weight 1.000
    item osd.1 weight 2.000
}
host host-b {
    id -2
    alg straw2
    hash 0
    item osd.2 weight 1.000
    item osd.3 weight 1.000
}
root default {
    id -3
    alg straw2
    hash 0
    item host-a weight 3.000
    item host-b weight 2.000
}

# rules
rule replicated_rule {
    id 0
    type replicated
    min_size 1
    max_size 10
    step take default
    step chooseleaf firstn 0 type host
    step emit
}
rule ec_rule {
    id 1
    type erasure
    step set_chooseleaf_tries 5
    step take default
    step choose indep 4 type osd
    step emit
}

# choose_args
choose_args 0 {
    {
        bucket_id -3
        weight_set [
            [ 1.000 4.000 ]
        ]
    }
}
# end crush map
"""


def test_compile_basic_structure():
    cm = compile_text(TEXT)
    assert set(cm.buckets) == {-1, -2, -3}
    assert cm.bucket_names == {-1: "host-a", -2: "host-b", -3: "default"}
    assert cm.buckets[-1].weights == [0x10000, 0x20000]
    assert cm.buckets[-3].items == [-1, -2]
    assert cm.type_names[10] == "root"
    assert cm.tunables.choose_total_tries == 50
    assert len(cm.rules) == 2
    assert cm.rules[0].steps == [
        (cmap.OP_TAKE, -3, 0), (cmap.OP_CHOOSELEAF_FIRSTN, 0, 1),
        (cmap.OP_EMIT, 0, 0)]
    assert cm.rules[1].type == 3
    assert cm.rules[1].steps[0] == (cmap.OP_SET_CHOOSELEAF_TRIES, 5, 0)
    assert cm.choose_args["0"] == {-3: [0x10000, 0x40000]}


def test_roundtrip_text_stable():
    cm = compile_text(TEXT)
    text2 = decompile(cm)
    cm2 = compile_text(text2)
    assert cm2.buckets.keys() == cm.buckets.keys()
    for bid in cm.buckets:
        assert cm2.buckets[bid].items == cm.buckets[bid].items
        assert cm2.buckets[bid].weights == cm.buckets[bid].weights
        assert cm2.buckets[bid].alg == cm.buckets[bid].alg
    assert [r.steps for r in cm2.rules] == [r.steps for r in cm.rules]
    assert cm2.choose_args == cm.choose_args
    assert cm2.bucket_names == cm.bucket_names
    # twice-decompiled text is byte-identical (stable output)
    assert decompile(cm2) == text2


def test_compiled_map_places_like_built_map():
    """A map built via the API and the same map compiled from text must
    place identically through the jit mapper."""
    cm_text = compile_text(TEXT)
    cm_api = cmap.CrushMap(cm_text.tunables)
    cm_api.add_bucket(cmap.ALG_STRAW2, 1, [0, 1], [0x10000, 0x20000],
                      id=-1)
    cm_api.add_bucket(cmap.ALG_STRAW2, 1, [2, 3], [0x10000, 0x10000],
                      id=-2)
    cm_api.add_bucket(cmap.ALG_STRAW2, 10, [-1, -2], [0x30000, 0x20000],
                      id=-3)
    steps = [(cmap.OP_TAKE, -3, 0), (cmap.OP_CHOOSELEAF_FIRSTN, 0, 1),
             (cmap.OP_EMIT, 0, 0)]
    xs = np.arange(512, dtype=np.int32)
    dev_w = np.full(4, 0x10000, dtype=np.uint32)
    out_text = mapper.compile_rule(cm_text.flatten(), steps, 2)(xs, dev_w)
    out_api = mapper.compile_rule(cm_api.flatten(), steps, 2)(xs, dev_w)
    assert np.array_equal(np.asarray(out_text), np.asarray(out_api))


def test_binary_codec_carries_names_and_choose_args():
    from ceph_tpu.core.encoding import Decoder, Encoder
    from ceph_tpu.osd.map_codec import decode_crush, encode_crush

    cm = compile_text(TEXT)
    e = Encoder()
    encode_crush(e, cm)
    cm2 = decode_crush(Decoder(e.bytes()))
    assert cm2.bucket_names == cm.bucket_names
    assert cm2.choose_args == cm.choose_args
    assert decompile(cm2) == decompile(cm)


def test_compile_errors():
    with pytest.raises(CompileError):
        compile_text("host h { id -1 item osd.0 weight 1.0 ")  # unclosed
    with pytest.raises(CompileError):
        compile_text("rule r { step frobnicate }")
    with pytest.raises(CompileError):
        compile_text("host h {\nid -1\nitem nosuch weight 1.0\n}")
