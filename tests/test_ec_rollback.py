"""EC write rollback / peering-liveness regression tests.

Covers the two halves of the round-6 robustness work:

- the `8f8fff3` watchdog regression: a fixed 1s re-kick tick kept
  restarting activations that lost the interval race, so the peering
  gate never opened and admitted ops starved behind an EAGAIN storm
  (HEAD was deterministically red on test_thrash_ec, op tid=30 t13);
- the rollback machinery: a shard that committed a stripe the
  authoritative log never saw must UNDO it from its persisted rollback
  records (reference ECBackend trim_to/roll_forward_to + PGLog
  divergent-entry handling) instead of converging by mark-missing +
  EAGAIN + re-replication.
"""

import sys, os
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_osd_cluster import MiniCluster, LibClient, EC_POOL

from ceph_tpu.osd import messages as m
from ceph_tpu.osd import types as t_
from ceph_tpu.osd.pg import PG, STATE_PEERING

EAGAIN = -11


def test_watchdog_backoff_not_fixed_tick():
    """Regression for the `8f8fff3` starvation loop: with a PG wedged
    in PEERING and every activation pass dying, the watchdog must
    re-kick on an exponentially backed-off fuse (1s, 2s, 4s, ...), not
    the old fixed 1s tick — and once activation can succeed again, the
    gate must open and admit client ops."""
    c = MiniCluster()
    cl = LibClient(c)
    try:
        io = cl.rc.ioctx(EC_POOL)
        oid = "wd0"
        assert io.operate(
            oid, [t_.OSDOp(t_.OP_WRITEFULL, data=b"x" * 4096)],
            timeout=15.0).result == 0
        pgid, acting, primary = c.primary_of(EC_POOL, oid)
        pg = c.osds[primary].pgs[pgid]

        kicks = []

        def dying_activate():
            kicks.append(time.monotonic())
            raise RuntimeError("activation loses the interval race")

        pg.activate = dying_activate  # instance shadow of PG.activate
        with pg.lock:
            pg.state = STATE_PEERING
            pg._peering_since = time.monotonic() - 10.0
            pg._wd_backoff = 0.0
            pg._wd_next = 0.0
        time.sleep(4.6)
        # fixed 1s tick would have re-kicked ~4 times; the exponential
        # fuse allows ~3 (at +0, +1, +2, [+4])
        assert 2 <= len(kicks) <= 4, (
            f"{len(kicks)} watchdog re-kicks in 4.6s at {kicks}: "
            "expected exponentially backed-off (~3), not a fixed tick")
        gaps = [b - a for a, b in zip(kicks, kicks[1:])]
        assert gaps and gaps[-1] > 1.5, (
            f"kick spacing never grew: {gaps}")

        # activation works again: the watchdog (or a direct kick) must
        # reopen the gate, and an admitted op completes
        del pg.activate
        pg.activate_async()
        c.osds[primary].wait_pgs_settled(15.0)
        assert pg.state != STATE_PEERING, "gate never reopened"
        rep = io.operate(oid, [t_.OSDOp(t_.OP_WRITEFULL,
                                        data=b"y" * 4096)], timeout=10.0)
        assert rep.result == 0, f"admitted op starved: rc={rep.result}"
    finally:
        cl.shutdown()
        c.shutdown()


def test_degraded_pg_admits_ops_promptly():
    """'Active accepts ops while recovery proceeds' (reference
    PG.h:1955): killing one EC member must not park client writes
    behind the peering gate while dead-peer RPC windows burn out —
    every write completes promptly against the degraded PG."""
    c = MiniCluster()
    cl = LibClient(c)
    down = None
    try:
        io = cl.rc.ioctx(EC_POOL)
        oids = [f"dg{i}" for i in range(8)]
        for i, oid in enumerate(oids):
            assert io.operate(
                oid, [t_.OSDOp(t_.OP_WRITEFULL,
                               data=f"{oid}-".encode() * 200)],
                timeout=15.0).result == 0
        down = 0
        c.kill(down)
        t0 = time.monotonic()
        for oid in oids:
            rep = io.operate(
                oid, [t_.OSDOp(t_.OP_WRITEFULL,
                               data=f"{oid}+".encode() * 200)],
                timeout=10.0)
            assert rep.result == 0, (
                f"write {oid} starved behind the peering gate: "
                f"rc={rep.result}")
        elapsed = time.monotonic() - t0
        assert elapsed < 16.0, (
            f"8 degraded writes took {elapsed:.1f}s — ops are "
            "serializing behind per-peer RPC windows")
        for oid in oids:
            rep = io.operate(oid, [t_.OSDOp(t_.OP_READ)], timeout=10.0)
            assert rep.result == 0
            assert rep.ops[0].out_data == f"{oid}+".encode() * 200
    finally:
        cl.shutdown()
        c.shutdown()


def test_kill_primary_mid_rmw_rolls_back():
    """Kill the primary after it committed an RMW stripe locally but
    before any other shard saw it.  On revival the leftover entry is
    divergent (committed by 1 < k members, above the roll-forward
    watermark): the revived shard must roll it BACK from its persisted
    rollback records — and convergence must produce ZERO client
    EAGAINs and no missing-object fallback for the oid (the old path:
    mark missing, EAGAIN until re-replication)."""
    c = MiniCluster()
    cl = LibClient(c)
    rollbacks = []
    orig_rb = PG._rollback_to

    def spy_rb(self, target):
        rollbacks.append((self.osd.whoami, self.pgid, str(target)))
        return orig_rb(self, target)

    try:
        io = cl.rc.ioctx(EC_POOL)
        oid = "rbk0"
        data = bytes(range(256)) * 256  # 64 KiB, deterministic
        assert io.operate(oid, [t_.OSDOp(t_.OP_WRITEFULL, data=data)],
                          timeout=15.0).result == 0
        pgid, acting, primary = c.primary_of(EC_POOL, oid)
        posd = c.osds[primary]
        pbackend = posd.pgs[pgid].backend

        # the mid-RMW crash: every outbound sub-write for this PG is
        # lost, so the stripe commits ONLY on the primary's own shard
        # (the backend captured osd.send_to_osd at construction, so the
        # hook must go on the backend itself)
        orig_send = pbackend.osd_send

        def drop_subwrites(osd_id, msg):
            if isinstance(msg, (m.MECSubWrite, m.MECSubWriteVec)):
                return
            orig_send(osd_id, msg)

        pbackend.osd_send = drop_subwrites
        patch, off = b"\xee" * 700, 1000
        # op timeout 2s < result wait: the objecter ticker synthesizes
        # an ETIMEDOUT reply and DEREGISTERS the op — no later resend
        # may re-apply the patch after convergence
        rep = io.aio_operate(oid, [t_.OSDOp(t_.OP_WRITE, off=off,
                                            data=patch)],
                             timeout=2.0).result(8.0)
        assert rep.result != 0, "write acked without shard quorum"
        pbackend.osd_send = orig_send

        PG._rollback_to = spy_rb
        eagains = []
        orig_dispatch = cl.rc.objecter.ms_dispatch

        def spy_dispatch(conn, msg):
            if isinstance(msg, m.MOSDOpReply) and msg.result == EAGAIN:
                eagains.append(msg.oid)
            return orig_dispatch(conn, msg)

        cl.rc.objecter.ms_dispatch = spy_dispatch

        c.kill(primary)    # survivors converge on the pre-RMW head
        c.revive(primary)  # divergent holder rejoins and must rewind

        assert rollbacks, (
            "divergent entry was never rolled back — convergence fell "
            "back to the re-replication path")
        assert any(pg_ == pgid for _, pg_, _t in rollbacks), rollbacks

        rep = io.operate(oid, [t_.OSDOp(t_.OP_READ)], timeout=15.0)
        assert rep.result == 0, f"first read after convergence: rc=" \
                                f"{rep.result}"
        assert rep.ops[0].out_data == data, (
            "rolled-back object does not match the pre-RMW image")
        assert not eagains, (
            f"{len(eagains)} EAGAIN replies during convergence "
            f"({eagains[:5]}): rollback should leave nothing to retry")
        # the revived holder must not have fallen back to mark-missing
        for osd in c.osds.values():
            pg = osd.pgs.get(pgid)
            if pg is not None:
                assert oid not in pg.missing, (
                    f"osd.{osd.whoami} marked {oid} missing — "
                    "re-replication fallback instead of rollback")
    finally:
        PG._rollback_to = orig_rb
        cl.shutdown()
        c.shutdown()
