"""SWAR packed-word GF(2^8) engine: pinned against the numpy GF
reference and the native C++ oracle (csrc/gf256.cc)."""

import numpy as np
import pytest

from ceph_tpu import _native
from ceph_tpu.ec import gf, matrices
from ceph_tpu.ops import gf256_swar


@pytest.mark.parametrize("shape", [(4, 2), (12, 8), (3, 3)])
@pytest.mark.parametrize("n", [4, 256, 1000, 4097])
def test_matches_gf_reference(shape, n):
    rng = np.random.default_rng(shape[0] * 1000 + n)
    R, k = shape
    mat = rng.integers(0, 256, size=(R, k), dtype=np.uint8)
    x = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    want = gf.matmul(mat, x)
    got = np.asarray(gf256_swar.gf_matmul_bytes(mat, x))
    assert np.array_equal(got, want)


def test_matches_native_oracle():
    k, m = 8, 4
    coding = matrices.isa_cauchy(k, m)
    rng = np.random.default_rng(7)
    x = rng.integers(0, 256, size=(k, 8192), dtype=np.uint8)
    want = _native.rs_encode(coding.astype(np.uint8), x)
    got = np.asarray(gf256_swar.gf_matmul_bytes(coding, x))
    assert np.array_equal(got, want)


def test_zero_and_identity_coefficients():
    mat = np.array([[1, 0], [0, 1], [0, 0]], dtype=np.uint8)
    x = np.arange(512, dtype=np.uint8).reshape(2, 256)
    got = np.asarray(gf256_swar.gf_matmul_bytes(mat, x))
    assert np.array_equal(got[0], x[0])
    assert np.array_equal(got[1], x[1])
    assert not got[2].any()
