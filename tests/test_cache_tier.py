"""Cache-tier dataplane tests (reference PrimaryLogPG cache-mode
writeback: promote on recency, proxy cold reads, agent flush/evict).
"""

import sys, os

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_osd_cluster import MiniCluster, LibClient, REP_POOL, EC_POOL

from ceph_tpu.client.cache_tier import CacheTier
from ceph_tpu.client.rados import RadosError


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


@pytest.fixture
def tier(client):
    # cache = replicated pool, base = EC pool (the classic deployment)
    return CacheTier(client.rc.ioctx(REP_POOL), client.rc.ioctx(EC_POOL),
                     hit_set_period=0.05, min_recency_for_promote=2,
                     capacity_objects=10)


def test_cold_reads_proxy_hot_reads_promote(tier):
    tier.base.write_full("warmme", b"base-copy")
    # first read: cold -> proxied, not cached
    assert tier.read("warmme") == b"base-copy"
    assert tier.proxied == 1 and tier.promotes == 0
    assert "warmme" not in tier.cache.list_objects()
    # heat it up across hit-set periods
    import time

    for _ in range(3):
        time.sleep(0.06)
        got = tier.read("warmme")
        assert got == b"base-copy"
    assert tier.promotes == 1
    assert "warmme" in tier.cache.list_objects()


def test_writeback_flush_and_evict(tier):
    tier.write_full("wb", b"dirty-data")
    # base hasn't seen it yet (writeback)
    with pytest.raises(RadosError):
        tier.base.read("wb")
    tier.flush("wb")
    assert tier.base.read("wb") == b"dirty-data"
    tier.evict("wb")
    assert "wb" not in tier.cache.list_objects()
    assert tier.read("wb") == b"dirty-data"  # proxied from base


def test_evict_refuses_dirty(tier):
    tier.write_full("dirtyobj", b"x")
    with pytest.raises(RadosError):
        tier.evict("dirtyobj")
    tier.flush("dirtyobj")
    tier.evict("dirtyobj")


def test_agent_flushes_cold_dirty_and_evicts_cold_clean(tier):
    import time

    for i in range(6):
        tier.write_full(f"cold{i}", b"d" * 64)
    # make one object hot so the agent keeps it
    for _ in range(3):
        time.sleep(0.06)
        tier.read("cold0")
    res = tier.agent_work(max_ops=4)
    assert res["flushed"], "agent must flush cold dirty objects"
    assert "cold0" not in res["flushed"][:1], "hottest flushes last"
    for oid in res["flushed"]:
        assert tier.base.read(oid) == b"d" * 64
    n = tier.flush_all()
    res2 = tier.agent_work(max_ops=10)
    for oid in res2["evicted"]:
        assert oid not in tier.cache.list_objects()


def test_remove_removes_both_tiers(tier):
    tier.write_full("gone", b"x")
    tier.flush("gone")
    tier.remove("gone")
    with pytest.raises(RadosError):
        tier.base.read("gone")
    with pytest.raises(RadosError):
        tier.cache.read("gone")
