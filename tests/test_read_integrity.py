"""Read-time integrity (PR 16): per-extent at-rest checksums on every
store, verify-on-read, and EC/replicated read-repair.

Conformance suite (every backend): writes seal crc32c per extent in
the same transaction, partial overwrites re-seal only touched extents,
ranged reads verify exactly the extents they serve, injected rot is
REFUSED at read time (never served, never a bare EIO), and FileStore's
WAL replay converges seals to file content after a torn apply.

End-to-end: a seeded flip on a PARTIALLY-OVERWRITTEN EC object — whose
hinfo crc is invalidated, the pre-seal blind spot — is caught at READ
time, served via reconstruction, counted (`read_verify_fail`,
`pg.scrub_errors` -> PG_DAMAGED feed) and auto-repaired; the
replicated path answers retryable while repair heals the primary."""

import os
import time

import pytest

from ceph_tpu.core.crc import crc32c
from ceph_tpu.osd import types as t_
from ceph_tpu.store import create
from ceph_tpu.store.filestore import FileStore
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import (
    ChecksumError,
    Collection,
    ExtentSeals,
    GHObject,
    Transaction,
)

from tests.test_osd_cluster import (EC_POOL, REP_POOL, LibClient,
                                    MiniCluster)

CID = Collection("1.0_head")
OID = GHObject("obj1")
E = 16  # small extent size: multi-extent objects stay tiny


@pytest.fixture(params=["memstore", "filestore", "blockstore"])
def store(request, tmp_path):
    s = create(request.param, path=str(tmp_path / "store"))
    s.csum_extent_size = E
    s.mkfs()
    s.mount()
    yield s
    s.umount()


def _mkcoll(store, cid=CID):
    t = Transaction()
    t.create_collection(cid)
    store.queue_transaction(t)


def _write(store, data, off=0, oid=OID):
    t = Transaction()
    t.write(CID, oid, off, data)
    store.queue_transaction(t)


def _seals(store, cid=CID, oid=OID):
    _data, _size, blob = store._read_span(cid, oid, 0, 0)
    return None if blob is None else ExtentSeals.from_bytes(blob)


def _extent_crcs(data, e=E):
    return [crc32c(bytes(data[i: i + e])) for i in range(0, len(data), e)]


# -- conformance: seal on write --------------------------------------------


def test_write_seals_every_extent(store):
    _mkcoll(store)
    data = b"A" * E + b"B" * E + b"C" * E + b"dd"  # 3 full + 2B tail
    _write(store, data)
    seals = _seals(store)
    assert seals is not None
    assert seals.extent_size == E
    assert seals.crcs == _extent_crcs(data)
    assert store.read(CID, OID) == data
    assert store.read(CID, OID, E + 3, 7) == data[E + 3: E + 10]


def test_partial_overwrite_reseals_only_touched_extents(store):
    _mkcoll(store)
    data = bytearray(b"0" * E + b"1" * E + b"2" * E + b"3" * E)
    _write(store, bytes(data))
    before = _seals(store).crcs
    # overwrite 8 bytes strictly inside extent 1
    patch = b"XYZWXYZW"
    _write(store, patch, off=E + 4)
    data[E + 4: E + 12] = patch
    after = _seals(store).crcs
    assert after == _extent_crcs(data)
    assert after[1] != before[1]
    assert [after[i] for i in (0, 2, 3)] == [before[i] for i in (0, 2, 3)]
    assert store.read(CID, OID) == bytes(data)


def test_append_truncate_zero_reseal(store):
    _mkcoll(store)
    data = bytearray(b"a" * (2 * E + 8))  # 2 full extents + 8B tail
    _write(store, bytes(data))
    # append through the tail extent into a new one
    tail = b"T" * E
    _write(store, tail, off=len(data))
    data += tail
    assert _seals(store).crcs == _extent_crcs(data)
    # truncate mid-extent
    t = Transaction()
    t.truncate(CID, OID, E + 5)
    store.queue_transaction(t)
    del data[E + 5:]
    assert _seals(store).crcs == _extent_crcs(data)
    # zero a range spanning the extent boundary
    t = Transaction()
    t.zero(CID, OID, E - 4, 6)
    store.queue_transaction(t)
    data[E - 4: E + 2] = b"\0" * 6
    assert _seals(store).crcs == _extent_crcs(data)
    assert store.read(CID, OID) == bytes(data)


def test_clone_and_rename_carry_consistent_seals(store):
    _mkcoll(store)
    cid2 = Collection("1.1_head")
    _mkcoll(store, cid2)
    data = b"clone-me" * (E // 2)  # multi-extent
    _write(store, data)
    dst = GHObject("obj1_clone")
    t = Transaction()
    t.clone(CID, OID, dst)
    store.queue_transaction(t)
    assert store.read(CID, dst) == data
    assert _seals(store, CID, dst).crcs == _extent_crcs(data)
    moved = GHObject("obj1_moved")
    t = Transaction()
    t.coll_move_rename(CID, dst, cid2, moved)
    store.queue_transaction(t)
    assert store.read(cid2, moved) == data
    assert _seals(store, cid2, moved).crcs == _extent_crcs(data)
    assert not store.exists(CID, dst)


# -- conformance: verify on read -------------------------------------------


def test_injected_rot_refused_at_read_time(store):
    """The PR-15 injection blind spot, closed: the corruption seam
    sits BEFORE the verify gate, so marked objects are refused — on
    whole AND ranged reads — instead of serving flipped bytes."""
    _mkcoll(store)
    data = b"rot-me--" * (E // 2)
    _write(store, data)
    store.debug_data_err_enabled = True
    store.debug_inject_data_err(CID, OID)
    fails0 = store.perf.value("read_verify_fail")
    with pytest.raises(ChecksumError):
        store.read(CID, OID)
    with pytest.raises(ChecksumError):
        store.read(CID, OID, 3, 5)  # ranged read routes the seam too
    assert store.perf.value("read_verify_fail") == fails0 + 2
    # verification off (the bench comparison knob): rot is SERVED
    store.verify_reads = False
    try:
        assert store.read(CID, OID) != data
    finally:
        store.verify_reads = True
    # a rewrite overwrites the bad media: mark drops, reads are clean
    _write(store, data)
    assert store.read(CID, OID) == data
    store.debug_data_err_enabled = False


def test_ranged_read_verifies_exactly_served_extents(store):
    """Physical rot in one extent: ranged reads of OTHER extents still
    serve (verify covers exactly what is read), any read covering the
    rotted extent refuses.  Backends with their own device layer
    (BlockStore) catch physical rot below the seal layer, so this
    physically flips bytes only where the test can reach the media."""
    _mkcoll(store)
    data = b"0" * E + b"1" * E + b"2" * E + b"3" * E
    _write(store, data)
    victim_off = 2 * E + 5  # inside extent 2
    if isinstance(store, MemStore):
        store._colls[CID][OID].data[victim_off] ^= 0x01
    elif isinstance(store, FileStore):
        path = store._datafile(CID, OID)
        with open(path, "r+b") as f:
            f.seek(victim_off)
            b = f.read(1)
            f.seek(victim_off)
            f.write(bytes([b[0] ^ 0x01]))
    else:
        pytest.skip("blockstore media rot is caught by its own "
                    "per-block device crc (covered elsewhere)")
    assert store.read(CID, OID, 0, 2 * E) == data[: 2 * E]  # clean extents
    assert store.read(CID, OID, 3 * E, E) == data[3 * E:]
    with pytest.raises(ChecksumError):
        store.read(CID, OID, 2 * E + 1, 4)  # covers the rotted extent
    with pytest.raises(ChecksumError):
        store.read(CID, OID)


def test_object_without_seals_reads_unverified(store):
    """Legacy tolerance: an object with NO seal record (pre-upgrade
    data, metadata-only objects) reads without verification rather
    than failing."""
    _mkcoll(store)
    data = b"legacy" * E
    _write(store, data)
    if isinstance(store, MemStore):
        store._colls[CID][OID].seals = None
    else:
        from ceph_tpu.store.kv import WriteBatch

        if isinstance(store, FileStore):
            from ceph_tpu.store.filestore import P_SEAL, _objkey
        else:
            from ceph_tpu.store.blockstore import P_SEAL, _objkey
        b = WriteBatch()
        b.rmkey(P_SEAL, _objkey(CID, OID))
        store._kv.submit(b)
    assert _seals(store) is None
    assert store.read(CID, OID) == data


def test_extent_size_change_verifies_at_stored_granularity(store):
    """Conf-resized extents: objects sealed at the OLD granularity
    still verify (whole-object re-read at the stored extent size)
    until a rewrite re-seals them at the new one."""
    _mkcoll(store)
    data = b"grain" * E
    _write(store, data)
    store.csum_extent_size = 2 * E
    assert store.read(CID, OID, 3, 10) == data[3:13]  # old-granularity
    assert store.read(CID, OID) == data
    _write(store, data)  # full rewrite re-seals at the new size
    seals = _seals(store)
    assert seals.extent_size == 2 * E
    assert seals.crcs == _extent_crcs(data, 2 * E)


def test_filestore_torn_tail_replay_reseals(tmp_path):
    """Crash consistency: a torn apply (WAL ahead of applied_seq, file
    bytes half-written) replays on mount and converges BOTH the file
    content and its seals — the replayed reads verify clean."""
    s = create("filestore", path=str(tmp_path / "fs"))
    s.csum_extent_size = E
    s.mkfs()
    s.mount()
    _mkcoll(s)
    base = b"b" * (3 * E)
    _write(s, base)
    seq_before = s._seq
    patch = b"P" * 10
    _write(s, patch, off=E + 2)  # the txn that will be "torn"
    expected = base[: E + 2] + patch + base[E + 12:]
    assert s.read(CID, OID) == expected
    # rewind applied_seq to before the patch and tear the patched
    # bytes on the media, then kill WITHOUT umount (umount would trim
    # the WAL): exactly the state a crash between the data write and
    # the seal/seq batch leaves behind
    from ceph_tpu.store.filestore import P_META
    from ceph_tpu.store.kv import WriteBatch

    b = WriteBatch()
    b.set(P_META, "applied_seq", str(seq_before).encode())
    s._kv.submit(b, sync=True)
    path = s._datafile(CID, OID)
    with open(path, "r+b") as f:
        f.seek(E + 2)
        f.write(b"\xff" * 5)  # half-applied patch
    s._kv.close()
    s._wal_fh.close()

    s2 = create("filestore", path=str(tmp_path / "fs"))
    s2.csum_extent_size = E
    s2.mount()
    assert s2.read(CID, OID) == expected  # replayed AND verifying
    assert _seals(s2).crcs == _extent_crcs(expected)
    s2.umount()


# -- end-to-end: EC read-repair --------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(overrides={"store_debug_inject_data_err": True})
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


def _pg_of(cluster, pool, oid):
    pgid, acting, primary = cluster.primary_of(pool, oid)
    return pgid, acting, primary, cluster.osds[primary].pgs[pgid]


def _rot_primary_shard(cluster, pool, oid):
    """Partial-overwrite `oid` (invalidating its hinfo crc — the
    pre-seal blind spot), then rot the PRIMARY's own shard."""
    pgid, acting, primary, pg = _pg_of(cluster, pool, oid)
    shard = acting.index(primary)
    coll = Collection(t_.pgid_str(pgid) + "_head")
    cluster.osds[primary].store.debug_inject_data_err(
        coll, GHObject(oid, shard=shard) if pool == EC_POOL
        else GHObject(oid))
    pg._obc_invalidate(oid)  # the write cached its projected state
    return pgid, shard, primary, pg, coll


def test_ec_read_detects_reconstructs_counts_and_auto_repairs(
        cluster, client):
    """THE acceptance regression: a seeded flip on a partially-
    overwritten EC object (invalid hinfo crc — undetectable by the
    whole-chunk crc check) is caught at READ time by the extent-seal
    gate, the client gets correct bytes via reconstruction, the
    failure is counted and health-attributed, and auto-repair heals
    the shard for a clean re-read."""
    base = b"read-integrity-" * 400
    patch = b"OVERWRITTEN!" * 20
    expected = base[:1000] + patch + base[1000 + len(patch):]

    # -- phase 1: attribution with auto-repair OFF
    cluster.ctx.conf.set_val("osd_scrub_auto_repair", False)
    client.put(EC_POOL, "ri_attr", base)
    client.op(EC_POOL, "ri_attr",
              [t_.OSDOp(t_.OP_WRITE, off=1000, data=patch)])
    pgid, shard, primary, pg, coll = _rot_primary_shard(
        cluster, EC_POOL, "ri_attr")
    store = cluster.osds[primary].store
    fails0 = store.perf.value("read_verify_fail")
    errs0 = pg.scrub_errors
    # the local shard fails verification -> ECRC -> decode around it:
    # the client NEVER sees the flip, and never a bare EIO
    assert client.get(EC_POOL, "ri_attr") == expected
    assert store.perf.value("read_verify_fail") > fails0
    assert pg.scrub_errors == errs0 + 1  # the PG_DAMAGED feed
    assert "ri_attr" in pg._read_repair_pending  # counted exactly once
    stat = next(s for s in cluster.osds[primary].pg_stats()
                if s.pgid == pgid)
    assert stat.scrub_errors >= 1
    # a re-read neither re-bumps nor re-queues (dedup)
    pg._obc_invalidate("ri_attr")
    assert client.get(EC_POOL, "ri_attr") == expected
    assert pg.scrub_errors == errs0 + 1

    # -- phase 2: the full heal loop with auto-repair ON
    cluster.ctx.conf.set_val("osd_scrub_auto_repair", True)
    try:
        client.put(EC_POOL, "ri_heal", base)
        client.op(EC_POOL, "ri_heal",
                  [t_.OSDOp(t_.OP_WRITE, off=1000, data=patch)])
        pgid2, shard2, primary2, pg2, coll2 = _rot_primary_shard(
            cluster, EC_POOL, "ri_heal")
        store2 = cluster.osds[primary2].store
        assert client.get(EC_POOL, "ri_heal") == expected
        # the async targeted repair rewrites the shard (clearing the
        # injected-rot mark) and takes the error count back down
        deadline = time.time() + 20.0
        while time.time() < deadline:
            with pg2.lock:
                if ("ri_heal" not in pg2._read_repair_pending
                        and pg2.scrub_errors == 0):
                    break
            time.sleep(0.05)
        assert pg2.scrub_errors == 0, "read-repair never settled"
        # the repaired shard reads clean straight from the store
        g = GHObject("ri_heal", shard=shard2)
        chunk = store2.read(coll2, g)
        assert chunk  # no ChecksumError: mark cleared by the rewrite
        pg2._obc_invalidate("ri_heal")
        assert client.get(EC_POOL, "ri_heal") == expected
        assert pg2.scrub_engine().run(deep=True) == {}
    finally:
        cluster.ctx.conf.set_val("osd_scrub_auto_repair", False)
        for o in cluster.osds.values():
            o.store.debug_clear_data_err()


def test_replicated_read_verify_fail_retries_and_heals(cluster, client):
    """Replicated pools: the primary's own rotted copy answers
    retryable (EAGAIN -> transparent objecter resend), never flipped
    bytes or EIO; auto-repair pulls the authoritative copy from a
    healthy replica and the retried read completes correctly."""
    cluster.ctx.conf.set_val("osd_scrub_auto_repair", True)
    payload = b"replicated-integrity" * 300
    try:
        client.put(REP_POOL, "rri0", payload)
        pgid, shard, primary, pg, coll = _rot_primary_shard(
            cluster, REP_POOL, "rri0")
        # the get blocks on EAGAIN-retry until the async repair heals
        # the primary's copy, then serves the true bytes
        assert client.get(REP_POOL, "rri0") == payload
        deadline = time.time() + 20.0
        while time.time() < deadline:
            with pg.lock:
                if ("rri0" not in pg._read_repair_pending
                        and pg.scrub_errors == 0):
                    break
            time.sleep(0.05)
        assert pg.scrub_errors == 0, "read-repair never settled"
        store = cluster.osds[primary].store
        assert store.read(coll, GHObject("rri0")) == payload
    finally:
        cluster.ctx.conf.set_val("osd_scrub_auto_repair", False)
        for o in cluster.osds.values():
            o.store.debug_clear_data_err()


def test_late_ecrc_reply_is_counted_and_fed_to_repair(cluster, client):
    """PR 17 satellite: a remote shard's checksum-failure (ECRC) reply
    that lands AFTER its read gather resolved used to be silently
    dropped — remote rot detected late was lost evidence.  It must be
    counted (read_verify_late) and still feed the dedup'd
    scrub_errors / read-repair attribution path."""
    from ceph_tpu.osd import messages as m_
    from ceph_tpu.osd.backend import ECRC

    cluster.ctx.conf.set_val("osd_scrub_auto_repair", False)
    payload = b"late-ecrc" * 300
    client.put(EC_POOL, "ri_late", payload)
    pgid, acting, primary, pg = _pg_of(cluster, EC_POOL, "ri_late")
    osd = cluster.osds[primary]
    captured = {}
    orig = osd.track_reads

    def spy(pgid_, cb, n):
        captured["cb"] = cb
        return orig(pgid_, cb, n)

    osd.track_reads = spy
    try:
        pg._obc_invalidate("ri_late")
        assert client.get(EC_POOL, "ri_late") == payload
    finally:
        osd.track_reads = orig
    cb = captured.get("cb")
    assert cb is not None, "EC read never gathered remotely"
    perf = osd.pg_perf
    late0 = perf.value("read_verify_late")
    errs0 = pg.scrub_errors
    # a healthy straggler (result=0) stays dropped: no counter motion
    cb(m_.MECSubReadReply(pgid, 0, shard=1, oid="ri_late", result=0))
    assert perf.value("read_verify_late") == late0
    assert pg.scrub_errors == errs0
    # an ECRC straggler is late rot evidence: counted + attributed
    cb(m_.MECSubReadReply(pgid, 0, shard=1, oid="ri_late",
                          result=ECRC))
    assert perf.value("read_verify_late") == late0 + 1
    assert pg.scrub_errors == errs0 + 1
    assert "ri_late" in pg._read_repair_pending
    # a second late verdict re-counts the REPLY but not the error
    # (the per-object dedup _note_read_verify_fail already enforces)
    cb(m_.MECSubReadReply(pgid, 0, shard=2, oid="ri_late",
                          result=ECRC))
    assert perf.value("read_verify_late") == late0 + 2
    assert pg.scrub_errors == errs0 + 1
    # don't leak damage state into the rest of the module
    with pg.lock:
        pg._read_repair_pending.discard("ri_late")
        pg.scrub_errors = errs0
