"""Windowed EC recovery engine + recover-on-read (osd/recovery.py).

The read-side twin of the PR-4 write-pipeline tests: W-object windowed
pulls land every object with correct _av stamps and an incrementally
draining pg.missing; sub-reads aggregate into ONE MECSubReadVec per
peer per round (not per object); a peer that only speaks legacy
MECSubRead still completes the window (mixed-version fallback); a peer
killed mid-window degrades to the survivors without losing window
slots; and a read of a missing object promotes it to the front of the
window and is served within one recovery round (recover-on-read)
instead of EAGAINing until the whole pull finishes.
"""

import sys, os
import threading
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.dirname(__file__))
from test_osd_cluster import EC_POOL, LibClient, MiniCluster, N_OSDS

from ceph_tpu.core.context import Context
from ceph_tpu.ec import codec_from_profile
from ceph_tpu.msg.message import EntityName
from ceph_tpu.osd import messages as m
from ceph_tpu.osd import types as t_
from ceph_tpu.osd.backend import _av_stamp, _hinfo
from ceph_tpu.osd.daemon import OSDService
from ceph_tpu.osd.pg import PG, STATE_DEGRADED, STATE_PEERING
from ceph_tpu.osd.types import EVersion, LogEntry
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import GHObject, Transaction

EAGAIN = -11


# ---------------------------------------------------------------------------
# stub harness: a real PG + ECBackend over a MemStore with a scripted
# "cluster" around it, so vec aggregation / fallback / peer-death paths
# are exercised deterministically without sockets
# ---------------------------------------------------------------------------


class _Perf:
    def __init__(self):
        self.vals = {}

    def inc(self, name, by=1):
        self.vals[name] = self.vals.get(name, 0) + by

    def set(self, name, v):
        self.vals[name] = v

    def value(self, name, default=0):
        return self.vals.get(name, default)


class _StubMap:
    def __init__(self, down=()):
        self.down = set(down)

    def is_up(self, o):
        return o not in self.down


class _StubOSD:
    """Duck-typed OSDService host: records sends, lets the test answer
    them (optionally through an auto-responder)."""

    def __init__(self, whoami, peers, conf=None):
        self.whoami = whoami
        self.ctx = Context(f"stub.osd{whoami}", conf or {})
        self.store = MemStore()
        self.store.mkfs()
        self.store.mount()
        self.addr_book = {p: ("stub", p) for p in peers}
        self.osdmap = _StubMap()
        self.sent = []
        self.responder = None  # fn(osd_id, msg) -> None
        self._read_cbs = {}
        self._tid = 0
        self._tid_lock = threading.Lock()
        self.perf = _Perf()
        self.pg_perf = _Perf()

    def epoch(self):
        return 7

    def _log(self, lvl, msg):
        pass

    def new_tid(self):
        with self._tid_lock:
            self._tid += 1
            return self._tid

    def track_reads(self, pgid, cb, count=None):
        tid = self.new_tid()
        self._read_cbs[tid] = cb
        return tid

    def untrack_reads(self, tid):
        self._read_cbs.pop(tid, None)

    def send_to_osd(self, osd_id, msg):
        self.sent.append((osd_id, msg))
        if self.responder is not None:
            self.responder(osd_id, msg)

    def reply(self, tid, rep):
        cb = self._read_cbs.get(tid)
        if cb is not None:
            cb(rep)

    def note_recovery_active(self, n):
        if n > self.pg_perf.vals.get("recovery_active", 0):
            self.pg_perf.set("recovery_active", n)


def _stub_pg(profile, acting, whoami=0, peers=(1, 2), conf=None):
    osd = _StubOSD(whoami, peers, conf=conf)
    codec = codec_from_profile(profile)
    pool = SimpleNamespace(size=len(acting), hit_set_count=0)
    pg = PG((3, 0), pool, osd, codec)
    t = Transaction()
    t.create_collection(pg.coll)
    osd.store.queue_transaction(t)
    with pg.lock:
        pg.acting = list(acting)
        pg.primary = whoami
        pg.state = STATE_DEGRADED
    return pg, osd


def _seed_missing(pg, oids, payload=b"r" * 4096):
    """Log entries + missing marks for `oids`; returns the per-oid
    chunk set a peer serves from (encoded with the pg's own codec)."""
    chunks = {}
    base = pg.log.head.version
    for i, oid in enumerate(sorted(oids)):
        v = EVersion(7, base + i + 1)
        data = oid.encode() + payload
        with pg.lock:
            pg.log.append(LogEntry(op=t_.LOG_MODIFY, oid=oid, version=v,
                                   prior_version=EVersion(0, 0)))
            pg.missing[oid] = v
        cs, _ = pg.backend._encode_object(data)
        chunks[oid] = (cs, v, data)
    return chunks


def _peer_row(chunks, oid, shard):
    cs, v, data = chunks[oid]
    attrs = {"hinfo": _hinfo(cs[shard], len(data)), "_av": _av_stamp(v)}
    return (shard, oid, cs[shard], 0, attrs, {})


def _vec_responder(osd, chunks, answer_peers=None, src_epoch=7):
    """Auto-answer vec (and legacy) sub-reads with the right chunks."""

    def respond(osd_id, msg):
        if answer_peers is not None and osd_id not in answer_peers:
            return
        if isinstance(msg, m.MECSubReadVec):
            rows = [_peer_row(chunks, oid, shard)
                    for shard, oid, _o, _l in msg.reads]
            rep = m.MECSubReadVecReply((3, 0), src_epoch, rows)
        elif isinstance(msg, m.MECSubRead):
            row = _peer_row(chunks, msg.oid, msg.shard)
            rep = m.MECSubReadReply((3, 0), src_epoch, msg.shard,
                                    msg.oid, row[2], 0, row[4], row[5])
        else:
            return
        rep.tid = msg.tid
        rep.src = EntityName("osd", osd_id)
        osd.reply(msg.tid, rep)

    return respond


def test_vec_subread_aggregation_one_msg_per_peer_per_round():
    """k=4,m=2 over 3 OSDs (each holds two shards): a 5-object window
    costs one MECSubReadVec per PEER per round — 2 rounds x 2 peers =
    4 messages, not 5 objects x 2 peers (let alone per shard) — and
    every object lands with the right chunk bytes and _av stamp."""
    pg, osd = _stub_pg("plugin=isa k=4 m=2 technique=reed_sol_van",
                       acting=[0, 1, 2, 0, 1, 2], peers=(1, 2))
    oids = [f"agg{i}" for i in range(5)]
    chunks = _seed_missing(pg, oids)
    osd.responder = _vec_responder(osd, chunks)
    pg.recovery_engine().recover(
        {oid: pg.log.latest_for(oid) for oid in oids})
    with pg.lock:
        assert not pg.missing, f"window left objects: {pg.missing}"
    vecs = [(o, v) for o, v in osd.sent if isinstance(v, m.MECSubReadVec)]
    assert vecs, "no vec sub-reads sent"
    assert len(vecs) == 4, (  # ceil(5/3)=2 rounds x 2 peers
        f"{len(vecs)} vec messages for 5 objects over 2 peers — "
        f"expected 4 (one per peer per round)")
    # first-round vecs carry all 3 objects' rows for both peer shards
    first = [v for _o, v in vecs[:2]]
    assert all(len(v.reads) == 6 for v in first), \
        [len(v.reads) for v in first]
    assert osd.pg_perf.vals.get("subread_msgs") == 4
    assert osd.pg_perf.vals.get("subread_ops") == 5
    assert osd.pg_perf.vals.get("recovery_active", 0) >= 3
    # decode really rode the batch queue (shards 0,3 were missing)
    assert osd.pg_perf.vals.get("decode_batch_jobs", 0) >= 1
    for oid in oids:
        cs, v, data = chunks[oid]
        for shard in (0, 3):
            g = GHObject(oid, shard=shard)
            assert osd.store.read(pg.coll, g) == cs[shard], \
                f"{oid} shard {shard}: wrong recovered bytes"
            assert osd.store.getattr(pg.coll, g, "_av") == _av_stamp(v)


def test_mixed_version_peer_falls_back_to_legacy_subreads():
    """One peer never answers the vec (an old build would not even
    decode it): after the read window it gets ONE legacy per-shard
    retry, the window still completes, and the peer is remembered as
    legacy-only — the next window skips the vec for it entirely."""
    pg, osd = _stub_pg(
        "plugin=isa k=4 m=2 technique=reed_sol_van",
        acting=[0, 1, 2, 0, 1, 2], peers=(1, 2),
        conf={"osd_recovery_read_timeout": 0.5})
    oids = ["mv0", "mv1"]
    chunks = _seed_missing(pg, oids)

    base = _vec_responder(osd, chunks)

    def legacy_peer1(osd_id, msg):
        if osd_id == 1 and isinstance(msg, m.MECSubReadVec):
            return  # peer 1 "cannot decode" the vec: silence
        base(osd_id, msg)

    osd.responder = legacy_peer1
    t0 = time.monotonic()
    pg.recovery_engine().recover(
        {oid: pg.log.latest_for(oid) for oid in oids})
    with pg.lock:
        assert not pg.missing, f"fallback never completed: {pg.missing}"
    assert time.monotonic() - t0 < 5.0
    legacy = [(o, v) for o, v in osd.sent
              if isinstance(v, m.MECSubRead) and o == 1]
    assert len(legacy) == 4, (  # 2 oids x peer 1's two shards
        f"expected 4 legacy sub-reads to the vec-less peer, "
        f"got {len(legacy)}")
    assert 1 in pg.recovery_engine()._no_vec
    # second window: peer 1 goes straight to legacy, peer 2 keeps vec
    osd.sent.clear()
    more = ["mv2", "mv3"]
    chunks2 = _seed_missing(pg, more, payload=b"s" * 4096)
    chunks.update(chunks2)
    pg.recovery_engine().recover(
        {oid: pg.log.latest_for(oid) for oid in more})
    with pg.lock:
        assert not pg.missing
    p1_msgs = [v for o, v in osd.sent if o == 1]
    assert p1_msgs and all(isinstance(v, m.MECSubRead) for v in p1_msgs)
    p2_msgs = [v for o, v in osd.sent if o == 2]
    assert p2_msgs and all(isinstance(v, m.MECSubReadVec)
                           for v in p2_msgs)


def test_kill_peer_mid_window_degrades_to_survivors():
    """k=2,m=2 over four holders: a peer that dies after the window's
    vec sub-reads went out must not burn the read timeout per object —
    peer_down fails its outstanding rows, and every object still
    recovers from the surviving k holders (no lost window slots)."""
    pg, osd = _stub_pg(
        "plugin=isa k=2 m=2 technique=reed_sol_van",
        acting=[0, 1, 2, 3], peers=(1, 2, 3),
        conf={"osd_recovery_read_timeout": 5.0})
    oids = [f"kp{i}" for i in range(4)]
    chunks = _seed_missing(pg, oids)
    held = []  # peer 1's vecs, answered only after the death below

    base = _vec_responder(osd, chunks)

    def respond(osd_id, msg):
        if osd_id == 3:
            return  # peer 3 dies before answering
        if osd_id == 1 and isinstance(msg, m.MECSubReadVec):
            held.append(msg)
            return
        base(osd_id, msg)

    osd.responder = respond
    done = []
    th = threading.Thread(
        target=lambda: (pg.recovery_engine().recover(
            {oid: pg.log.latest_for(oid) for oid in oids}),
            done.append(1)),
        daemon=True)
    t0 = time.monotonic()
    th.start()
    deadline = time.monotonic() + 5.0
    while not held and time.monotonic() < deadline:
        time.sleep(0.02)
    assert held, "peer 1 never got its vec"
    # the map marks peer 3 down mid-window
    osd.osdmap = _StubMap(down={3})
    pg.note_peers_down({3})
    for msg in held:  # peer 1 answers late
        base(1, msg)
    held.clear()
    osd.responder = lambda o, v: (None if o == 3 else base(o, v))
    th.join(timeout=10.0)
    assert done, "window wedged after mid-window peer death"
    # fail-fast: nothing waited out the 5s read timeout on peer 3
    assert time.monotonic() - t0 < 4.5
    with pg.lock:
        assert not pg.missing, f"lost window slots: {pg.missing}"


def test_park_read_serves_after_recovery_and_times_out_honestly():
    pg, osd = _stub_pg(
        "plugin=isa k=4 m=2 technique=reed_sol_van",
        acting=[0, 1, 2, 0, 1, 2], peers=(1, 2),
        conf={"osd_recovery_read_timeout": 0.4})
    chunks = _seed_missing(pg, ["pk0"])
    osd.responder = _vec_responder(osd, chunks)
    got = []
    ev = threading.Event()
    assert pg.recovery_engine().park_read(
        "pk0", lambda ok: (got.append(ok), ev.set()))
    assert ev.wait(10.0), "parked read never woken"
    assert got == [True]
    with pg.lock:
        assert "pk0" not in pg.missing
    # an object nobody can serve: the parked read answers False
    # (EAGAIN) within the bounded wait, not never
    _seed_missing(pg, ["pk1"], payload=b"t" * 4096)
    osd.responder = None  # every peer silent now
    got2, ev2 = [], threading.Event()
    assert pg.recovery_engine().park_read(
        "pk1", lambda ok: (got2.append(ok), ev2.set()))
    assert ev2.wait(10.0), "bounded wait never fired"
    assert got2 == [False]
    # already-recovered object: park refuses, caller re-checks
    assert not pg.recovery_engine().park_read("pk0", lambda ok: None)


# ---------------------------------------------------------------------------
# cluster integration: the real pull path over sockets
# ---------------------------------------------------------------------------


def _same_pg_oids(c, n, prefix):
    """n object names all landing in one EC pg; returns (pgid, oids)."""
    target = c.osdmap.object_to_pg(EC_POOL, f"{prefix}0")
    oids = []
    i = 0
    while len(oids) < n:
        oid = f"{prefix}{i}"
        if c.osdmap.object_to_pg(EC_POOL, oid) == target:
            oids.append(oid)
        i += 1
        assert i < 2000, "could not find same-pg names"
    return target, oids


def _revive_hooked(c, osd_id, pre_activate=None):
    """MiniCluster.revive with a hook between daemon construction and
    activation (to wrap send_to_osd etc.), optionally without the
    settle wait."""
    from tests.test_osd_cluster import MiniCluster as _MC  # noqa: F401

    old = c.osds[osd_id]
    svc = OSDService(c.ctx, osd_id, old.store, c.osdmap,
                     codec_from_profile)
    svc.init()
    c.osds[osd_id] = svc
    if pre_activate is not None:
        pre_activate(svc)
    c.osdmap.set_osd_up(osd_id)
    c.refresh()
    for o in c.osds.values():
        if o.up:
            o.activate_pgs()
    return svc


def test_windowed_pull_end_to_end():
    """Kill an EC pg's primary, write 8 objects degraded, revive it:
    the revived primary recovers every object through the windowed
    engine — aggregated vec sub-reads (< 1 message per object per
    peer), correct post-recovery bytes and _av stamps, drained
    missing set, and a recovery_active high-water > 1."""
    c = MiniCluster()
    cl = LibClient(c)
    try:
        pgid, oids = _same_pg_oids(c, 8, "wp")
        _pg, acting, primary = c.primary_of(EC_POOL, oids[0])
        for oid in oids:
            assert cl.put(EC_POOL, oid,
                          f"{oid}-v1".encode() * 100).result == 0
        c.kill(primary)
        for oid in oids:
            assert cl.put(EC_POOL, oid,
                          f"{oid}-v2".encode() * 100).result == 0

        vec_msgs = []

        def hook(svc):
            orig = svc.send_to_osd

            def spy(osd_id, msg):
                if isinstance(msg, m.MECSubReadVec) \
                        and msg.pgid == pgid:
                    vec_msgs.append((osd_id, msg))
                orig(osd_id, msg)

            svc.send_to_osd = spy

        svc = _revive_hooked(c, primary, pre_activate=hook)
        for o in c.osds.values():
            if o.up:
                o.wait_pgs_settled(20.0)
        pg = svc.pgs[pgid]
        with pg.lock:
            assert not pg.missing, f"pull left missing: {pg.missing}"
        for oid in oids:
            assert cl.get(EC_POOL, oid) == f"{oid}-v2".encode() * 100
        assert vec_msgs, "pull never used vec sub-reads"
        # aggregation: 8 objects over 2 peers at W=3 is <= 6 vecs;
        # the old shape was one message per (object, peer) = 16
        assert len(vec_msgs) <= 8, (
            f"{len(vec_msgs)} vec messages for 8 objects — "
            "window aggregation is not happening")
        perf = svc.pg_perf.dump()
        assert perf.get("recovery_active", 0) >= 2, perf
        assert perf.get("subread_ops", 0) >= 8, perf
        # recovered shards carry the newest entry's _av stamp
        n = pg.backend.k + pg.backend.m
        my_shards = pg.backend.local_shards(pg.acting[:n])
        for oid in oids:
            en = pg.log.latest_for(oid)
            for shard in my_shards:
                got = svc.store.getattr(pg.coll,
                                        GHObject(oid, shard=shard),
                                        "_av")
                assert got == _av_stamp(en.version), \
                    f"{oid} shard {shard}: stale recovery stamp"
    finally:
        cl.shutdown()
        c.shutdown()


def test_recover_on_read_serves_before_full_pull():
    """With a slow 16-object pull at window W=1, a read of an object
    deep in the queue promotes it and is served by its own recovery
    round — while most of the pull is still outstanding — instead of
    EAGAINing until the end (recover_on_read_hits proves the parked
    read was woken by recovery, not by luck)."""
    c = MiniCluster()
    cl = LibClient(c)
    c.ctx.conf.set_val("osd_recovery_max_active", 1, force=True)
    try:
        pgid, oids = _same_pg_oids(c, 16, "rr")
        _pg, acting, primary = c.primary_of(EC_POOL, oids[0])
        for oid in oids:
            assert cl.put(EC_POOL, oid,
                          f"{oid}|A".encode() * 64).result == 0
        c.kill(primary)
        for oid in oids:
            assert cl.put(EC_POOL, oid,
                          f"{oid}|B".encode() * 64).result == 0
        # slow every surviving peer's vec answer: ~0.15s per window
        # round makes the 16-round pull take seconds
        for o in c.osds.values():
            if not o.up or pgid not in o.pgs:
                continue
            opg = o.pgs[pgid]
            orig = opg.handle_sub_read_vec

            def slow(msg, conn, _orig=orig):
                time.sleep(0.15)
                _orig(msg, conn)

            opg.handle_sub_read_vec = slow
        svc = _revive_hooked(c, primary)  # no settle wait
        pg = svc.pgs[pgid]
        target = sorted(oids)[-1]  # recovered LAST in queue order
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            with pg.lock:
                started = (pg.state != STATE_PEERING
                           and target in pg.missing
                           and len(pg.missing) > 8)
            if started:
                break
            time.sleep(0.05)
        assert started, "pull drained before the read could race it"
        rep = cl.op(EC_POOL, target, [t_.OSDOp(t_.OP_READ)],
                    timeout=15.0)
        assert rep.result == 0, f"promoted read failed: {rep.result}"
        assert rep.ops[0].out_data == f"{target}|B".encode() * 64
        with pg.lock:
            left = len(pg.missing)
        assert left > 0, (
            "read only completed after the full pull — promotion "
            "did not shortcut the window")
        hits = svc.pg_perf.dump().get("recover_on_read_hits", 0)
        assert hits >= 1, "no parked read was woken by recovery"
        for o in c.osds.values():
            if o.up:
                o.wait_pgs_settled(30.0)
        for oid in oids:
            assert cl.get(EC_POOL, oid) == f"{oid}|B".encode() * 64
    finally:
        c.ctx.conf.set_val("osd_recovery_max_active", 3, force=True)
        cl.shutdown()
        c.shutdown()
