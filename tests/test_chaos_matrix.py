"""Deterministic chaos scenario matrix (the teuthology thrashosds +
background-task analog): the EC rados-model sequence — with its
acked-durability oracle — runs while seeded OSD kills AND a scenario's
churn run concurrently:

  scrub  always-on deep scrub + auto-repair over seeded silent
         corruption (store.corrupt_chunk, unrestricted rot namespace:
         full-write AND partially-overwritten targets)
  tier   cache-tier write/promote/flush/evict churn
  snap   selfmanaged snap create / clone / trim churn
  read   the same unrestricted rot under concurrent client reads:
         read-time integrity (PR 16) must serve true bytes via
         reconstruction, never flipped data
  all    every churn at once (the acceptance chaos matrix)

One fast representative per scenario runs in tier-1 (seconds each, one
fixed seed); the multi-seed grids live behind -m slow.  The scenario
machinery itself is tools/thrash_hunt.py::run_scenario — the same code
an operator drives with `thrash_hunt.py --scenario ...`."""

import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, os.path.abspath(TOOLS))

import thrash_hunt  # noqa: E402


def test_chaos_scenario_scrub_fast():
    """Deep scrub + auto-repair over seeded rot, concurrent with kills
    and the model oracle: repairs fire (the corruption schedule is
    asserted to have fired), rot objects read clean at the end, and no
    acked model data is harmed."""
    assert thrash_hunt.run_scenario(0xC405, "scrub", rounds=40)


def test_chaos_scenario_tier_fast():
    assert thrash_hunt.run_scenario(0xC406, "tier", rounds=40)


def test_chaos_scenario_snap_fast():
    assert thrash_hunt.run_scenario(0xC407, "snap", rounds=40)


def test_chaos_scenario_read_integrity_fast():
    """Seeded rot on full-write AND appended-to (invalid hinfo crc)
    EC objects under concurrent client reads and kills: every read
    serves true bytes via the extent-seal gate + reconstruction, the
    detection is counted at READ time (read_verify_fail), and the
    corruption schedule is asserted to have fired."""
    assert thrash_hunt.run_scenario(0xC409, "read", rounds=40)


def test_chaos_scenario_combined_fast():
    """One combined (scrub+tier+snap churn concurrent with kills and
    injected corruption) representative in tier-1."""
    assert thrash_hunt.run_scenario(0xC408, "all", rounds=40)


@pytest.mark.slow
def test_chaos_matrix_ten_seeds_combined():
    """The acceptance grid: >= 10 seeds of the combined scenario, all
    green with the acked-durability oracle."""
    assert thrash_hunt.run_scenario_matrix(
        0xC408, ["all"], rounds=80, tries=10) == 0


@pytest.mark.slow
def test_chaos_matrix_per_scenario_seeds():
    """Per-scenario seed sweeps (scrub/tier/snap/read), the
    `thrash_hunt.py --scenario matrix` grid."""
    assert thrash_hunt.run_scenario_matrix(
        0xC410, ["scrub", "tier", "snap", "read"], rounds=80,
        tries=4) == 0
