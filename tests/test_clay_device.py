"""Clay repair on the device queue (PR 19): the batched coupled-layer
kernels ("crep"/"cdec" StripeBatchQueue kinds) are bit-exact against
the host codec API across (k,m,d) configs — ragged tails and every
lost-shard index included — and a degraded clay pool recovers through
the SUB-CHUNK read plan end to end: one MECSubReadVec runs tail per
helper, layers-only wire payloads, the repair_read_frac gauge landing
at ~d/(k*q), and the recovered shard carrying the recovery _av stamp.
"""

import sys, os
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_recovery_pipeline import _seed_missing, _stub_pg

from ceph_tpu.ec.clay import ClayCodec
from ceph_tpu.msg.message import EntityName
from ceph_tpu.osd import messages as m
from ceph_tpu.osd.backend import _av_stamp, _hinfo
from ceph_tpu.store.objectstore import GHObject
from ceph_tpu.tpu.queue import StripeBatchQueue


def _chunks(codec, s, seed=0):
    """Random data planes [k, Z*s] + parity via the codec: returns the
    full chunk list (row i = chunk i, flat uint8 [Z*s])."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=(codec.k, codec.sub_count * s),
                        dtype=np.uint8)
    parity = np.asarray(codec.encode_array(data), dtype=np.uint8)
    return [np.ascontiguousarray(r) for r in np.vstack([data, parity])]


def _repair_planes(codec, chunks, lost, s):
    """Layers-only helper planes [d, L, s] for a single-shard repair —
    exactly what the sub-chunk read plan pulls over the wire."""
    layers = codec.repair_layers(lost)
    helpers = [i for i in range(codec.k + codec.m) if i != lost][:codec.d]
    planes = np.stack([
        chunks[h].reshape(codec.sub_count, s)[layers] for h in helpers])
    return helpers, planes


def _sweep_crep(k, m, s, seed):
    """Every lost-shard index through the queue's crep kind: the device
    result must match BOTH the original chunk and the host repair API."""
    codec = ClayCodec(k=k, m=m)
    chunks = _chunks(codec, s, seed=seed)
    q = StripeBatchQueue(window_s=0.001)
    try:
        for lost in range(k + m):
            helpers, planes = _repair_planes(codec, chunks, lost, s)
            got = np.asarray(q.clay_repair(codec, lost, helpers, planes))
            np.testing.assert_array_equal(
                got, chunks[lost].ravel(),
                err_msg=f"k{k}m{m} s={s}: device repair of shard {lost}")
            host = codec.repair_chunk(
                [lost], {h: chunks[h] for h in helpers})[lost]
            np.testing.assert_array_equal(
                got, np.asarray(host).ravel(),
                err_msg=f"k{k}m{m} s={s}: device vs host, shard {lost}")
    finally:
        q.stop()


def _sweep_cdec(k, m, s, seed):
    """Erasure patterns through the queue's cdec kind vs the host
    decode: data planes must come back bit-exact."""
    codec = ClayCodec(k=k, m=m)
    chunks = _chunks(codec, s, seed=seed)
    want = np.stack(chunks[:k])
    q = StripeBatchQueue(window_s=0.001)
    rng = np.random.default_rng(seed + 1)
    try:
        for _ in range(4):
            n_erase = int(rng.integers(1, m + 1))
            erased = set(rng.choice(k + m, size=n_erase,
                                    replace=False).tolist())
            avail = {i: chunks[i] for i in range(k + m) if i not in erased}
            got = np.asarray(q.clay_decode_async(codec, avail).result())
            np.testing.assert_array_equal(
                got, want, err_msg=f"k{k}m{m} s={s}: erased={erased}")
    finally:
        q.stop()


def test_crep_device_bit_exact_every_lost_shard_k4m2():
    # s=40: a ragged (non-pow2) per-layer width — the covering pad in
    # _dispatch_array must never leak into real bytes
    _sweep_crep(4, 2, s=40, seed=3)


def test_cdec_device_bit_exact_k4m2():
    _sweep_cdec(4, 2, s=40, seed=7)


def test_crep_ragged_tail_widths():
    """Odd per-layer widths (1, 5, 7 bytes) through the bucketed
    dispatch: the smallest shapes stress the pad-then-slice path."""
    codec = ClayCodec(k=4, m=2)
    q = StripeBatchQueue(window_s=0.001)
    try:
        for s in (1, 5, 7):
            chunks = _chunks(codec, s, seed=s)
            lost = 3
            helpers, planes = _repair_planes(codec, chunks, lost, s)
            got = np.asarray(q.clay_repair(codec, lost, helpers, planes))
            np.testing.assert_array_equal(
                got, chunks[lost].ravel(), err_msg=f"s={s}")
    finally:
        q.stop()


@pytest.mark.parametrize("k,m,s", [(8, 4, 33), (5, 3, 17)])
def test_crep_device_bit_exact_full_matrix(k, m, s):
    """Bigger geometries (k8m4 = the paper's headline config, k5m3 =
    shortened construction with a virtual node) across every lost
    shard, ragged widths — small widths keep this tier-1 fast."""
    _sweep_crep(k, m, s=s, seed=k * 31 + m)
    _sweep_cdec(k, m, s=s, seed=k * 37 + m)


def test_crep_jobs_coalesce_into_one_batch():
    """Concurrent repairs of the SAME lost shard (a recovery window
    draining one dead OSD) must coalesce along the S axis — and every
    job in the batch still comes back bit-exact."""
    codec = ClayCodec(k=4, m=2)
    q = StripeBatchQueue(window_s=0.25)
    try:
        jobs = []
        for seed in range(6):
            chunks = _chunks(codec, 24, seed=seed)
            helpers, planes = _repair_planes(codec, chunks, 2, 24)
            jobs.append((chunks, q.clay_repair_async(
                codec, 2, helpers, planes)))
        for chunks, fut in jobs:
            np.testing.assert_array_equal(
                np.asarray(fut.result()), chunks[2].ravel())
        # 6 jobs enqueued within one coalescing window: at most the
        # first dispatches alone before the rest pile up
        assert q.batches <= 3, f"{q.batches} batches for 6 same-sig jobs"
        assert max(q.dec_batch_jobs) >= 2, q.dec_batch_jobs
    finally:
        q.stop()


# ---------------------------------------------------------------------------
# degraded clay pool, end to end: sub-chunk plan -> layers-only wire ->
# crep kernel -> _store_repaired, with the counter evidence
# ---------------------------------------------------------------------------

CLAY_PROFILE = "plugin=clay k=8 m=4 d=11"


def _clay_vec_responder(osd, chunks, Z, src_epoch=7, mute=()):
    """Answer MECSubReadVec honoring the v2 runs tail: a row with runs
    gets ONLY those sub-chunk extents back (served=1), an empty-runs
    row gets the whole chunk (served=0) — a peer in `mute` never
    answers rows that carry runs (plan-failure injection)."""

    def respond(osd_id, msg):
        if not isinstance(msg, m.MECSubReadVec):
            return
        run_plans = (msg.runs if len(msg.runs) == len(msg.reads)
                     else [[] for _ in msg.reads])
        if osd_id in mute and any(run_plans):
            return
        rows, served = [], []
        for (shard, oid, _o, _l), rr in zip(msg.reads, run_plans):
            cs, v, data = chunks[oid]
            chunk = bytes(cs[shard])
            attrs = {"hinfo": _hinfo(cs[shard], len(data)),
                     "_av": _av_stamp(v)}
            if rr:
                sub = len(chunk) // Z
                blob = b"".join(chunk[so * sub:(so + cnt) * sub]
                                for so, cnt in rr)
                rows.append((shard, oid, blob, 0, attrs, {}))
                served.append(1)
            else:
                rows.append((shard, oid, chunk, 0, attrs, {}))
                served.append(0)
        rep = m.MECSubReadVecReply((3, 0), src_epoch, rows, served=served)
        rep.tid = msg.tid
        rep.src = EntityName("osd", osd_id)
        osd.reply(msg.tid, rep)

    return respond


def test_clay_degraded_recovery_uses_subchunk_plan_e2e():
    """k=8,m=4,d=11 clay pool, primary missing its single local shard
    for a window of objects: recovery sends per-helper RUN tails, the
    wire carries only repair layers, every object lands with correct
    chunk bytes + recovery _av stamp, and repair_read_frac measures
    ~d/(k*q) = 344 permille — the ISSUE's <= 0.4 acceptance."""
    pg, osd = _stub_pg(CLAY_PROFILE, acting=list(range(12)),
                       whoami=0, peers=tuple(range(1, 12)))
    Z = pg.backend.codec.get_sub_chunk_count()
    oids = [f"clay{i}" for i in range(3)]
    chunks = _seed_missing(pg, oids)
    osd.responder = _clay_vec_responder(osd, chunks, Z)
    pg.recovery_engine().recover(
        {oid: pg.log.latest_for(oid) for oid in oids})
    with pg.lock:
        assert not pg.missing, f"window left objects: {pg.missing}"
    # the plan actually engaged: every helper's vec row carried runs
    vecs = [v for _o, v in osd.sent if isinstance(v, m.MECSubReadVec)]
    assert vecs and all(
        all(rr for rr in v.runs) for v in vecs), \
        [v.runs for v in vecs]
    # layers-only wire: the ratio gauge sits at the MSR point
    frac = osd.pg_perf.value("repair_read_frac")
    assert 0 < frac <= 400, f"repair_read_frac={frac} permille"
    assert osd.pg_perf.value("subread_bytes") > 0
    # the repair rode the device queue, not a host bypass
    assert osd.pg_perf.value("decode_batch_jobs") >= 1
    for oid in oids:
        cs, v, _data = chunks[oid]
        g = GHObject(oid, shard=0)
        assert osd.store.read(pg.coll, g) == bytes(cs[0]), \
            f"{oid}: wrong repaired bytes"
        assert osd.store.getattr(pg.coll, g, "_av") == _av_stamp(v)


def test_clay_plan_helper_failure_falls_back_whole_chunk():
    """A helper that never answers the sub-chunk round: attempt 1 times
    out retryable, attempt 2 re-gathers WHOLE chunks (no runs) and the
    object still lands — the plan can only save bytes, never lose an
    object."""
    pg, osd = _stub_pg(CLAY_PROFILE, acting=list(range(12)),
                       whoami=0, peers=tuple(range(1, 12)),
                       conf={"osd_recovery_read_timeout": 0.5})
    Z = pg.backend.codec.get_sub_chunk_count()
    chunks = _seed_missing(pg, ["cfb0"])
    osd.responder = _clay_vec_responder(osd, chunks, Z, mute={11})
    t0 = time.monotonic()
    pg.recovery_engine().recover({"cfb0": pg.log.latest_for("cfb0")})
    assert time.monotonic() - t0 < 8.0
    with pg.lock:
        assert not pg.missing, "fallback never landed the object"
    cs, v, _data = chunks["cfb0"]
    g = GHObject("cfb0", shard=0)
    assert osd.store.read(pg.coll, g) == bytes(cs[0])
    assert osd.store.getattr(pg.coll, g, "_av") == _av_stamp(v)
    # both rounds visible: a runs round, then a whole-chunk round
    vecs = [v_ for _o, v_ in osd.sent if isinstance(v_, m.MECSubReadVec)]
    assert any(any(rr for rr in v_.runs) for v_ in vecs)
    assert any(not any(rr for rr in v_.runs) for v_ in vecs)
    # the whole-chunk retry pushes the running ratio past the plan's
    # 344 permille — honest accounting, not a vanity gauge
    assert osd.pg_perf.value("repair_read_frac") > 344


# ---------------------------------------------------------------------------
# clay pool under OSD thrashing: the acked-durability oracle
# (test_rados_model's model sequence) + thrash_hunt's forensics hooks,
# the same bar the RS pools clear
# ---------------------------------------------------------------------------

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, os.path.abspath(TOOLS))


def test_thrash_clay_model_oracle():
    """One seeded kill/revive thrash on the clay pool while the rados
    model sequence runs: every acked op must be durable and readable
    (failures dump shard-level forensics via thrash_hunt)."""
    import thrash_hunt

    assert thrash_hunt.run_one(0xC1A9, "clay", rounds=60)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(10))
def test_thrash_clay_matrix(seed):
    """The acceptance grid: ten seeds of model-under-thrash on the
    clay pool, all green — sub-chunk repair plans, their whole-chunk
    fallbacks, and plain degraded ops interleave freely here."""
    import thrash_hunt

    assert thrash_hunt.run_one(0xC1A0 + seed, "clay", rounds=80)
