"""Core runtime layer tests: encoding, crc, config, perf, throttle, wq.

Mirrors the reference's src/test/common/ + src/test/encoding/ tier
(SURVEY.md §4 tier 1).
"""

import os
import threading
import time

import pytest

from ceph_tpu.core import crc
from ceph_tpu.core.admin_socket import admin_command
from ceph_tpu.core.config import Config, SCHEMA
from ceph_tpu.core.context import Context
from ceph_tpu.core.encoding import Decoder, DecodeError, Encoder
from ceph_tpu.core.heartbeat import HeartbeatMap
from ceph_tpu.core.log import Log
from ceph_tpu.core.perf import PerfCounters
from ceph_tpu.core.throttle import Throttle
from ceph_tpu.core.workqueue import ShardedWorkQueue


# -- encoding ---------------------------------------------------------------


def test_encoding_primitives_roundtrip():
    e = Encoder()
    e.u8(7).u16(300).u32(1 << 30).u64(1 << 50).s32(-5).s64(-(1 << 40))
    e.f64(3.25).boolean(True).string("héllo").blob(b"\x00\xff")
    e.seq([1, 2, 3], lambda enc, v: enc.u32(v))
    e.mapping({"b": 2, "a": 1}, lambda enc, k: enc.string(k),
              lambda enc, v: enc.u32(v))
    e.optional(None, lambda enc, v: enc.u32(v))
    e.optional(9, lambda enc, v: enc.u32(v))
    d = Decoder(e.bytes())
    assert d.u8() == 7
    assert d.u16() == 300
    assert d.u32() == 1 << 30
    assert d.u64() == 1 << 50
    assert d.s32() == -5
    assert d.s64() == -(1 << 40)
    assert d.f64() == 3.25
    assert d.boolean() is True
    assert d.string() == "héllo"
    assert d.blob() == b"\x00\xff"
    assert d.seq(lambda dec: dec.u32()) == [1, 2, 3]
    assert d.mapping(lambda dec: dec.string(), lambda dec: dec.u32()) == {
        "a": 1, "b": 2,
    }
    assert d.optional(lambda dec: dec.u32()) is None
    assert d.optional(lambda dec: dec.u32()) == 9


def test_encoding_version_skew_forward_compat():
    # a v2 encoder writes an extra field; a v1-era decoder must skip it
    # (ENCODE_START/DECODE_FINISH semantics, src/include/encoding.h)
    e = Encoder()
    e.start(version=2, compat=1)
    e.u32(42).string("v2-only-extra")
    e.finish()
    e.u32(0xDEAD)  # trailing sibling field

    d = Decoder(e.bytes())
    v = d.start(compat_supported=1)
    assert v == 2
    assert d.u32() == 42
    d.end()  # skips the unknown string
    assert d.u32() == 0xDEAD


def test_encoding_compat_rejects_too_new():
    e = Encoder()
    e.start(version=5, compat=4)
    e.u32(1)
    e.finish()
    d = Decoder(e.bytes())
    with pytest.raises(DecodeError):
        d.start(compat_supported=3)


def test_decode_underrun_raises():
    with pytest.raises(DecodeError):
        Decoder(b"\x01").u32()


# -- crc32c -----------------------------------------------------------------


def test_crc32c_known_vectors():
    # standard castagnoli check value
    assert crc.crc32c(b"123456789") == 0xE3069283
    assert crc.crc32c(b"") == 0
    # chaining == one-shot
    whole = crc.crc32c(b"foobar")
    part = crc.crc32c(b"bar", crc.crc32c(b"foo"))
    assert whole == part


def test_crc32c_native_matches_python(monkeypatch):
    data = os.urandom(1000)
    native = crc.crc32c(data)
    monkeypatch.setattr(crc, "_native", False)
    assert crc.crc32c(data) == native


# -- config -----------------------------------------------------------------


def test_config_defaults_and_set():
    c = Config()
    assert c.get("osd_pool_default_size") == 3
    c.set_val("osd_pool_default_size", "5")
    assert c.osd_pool_default_size == 5
    with pytest.raises(ValueError):
        c.set_val("objectstore", "not-a-backend")
    with pytest.raises(KeyError):
        c.set_val("no_such_option", 1)


def test_config_observer_fires_on_apply():
    c = Config()
    seen = []
    c.add_observer(("osd_heartbeat_grace",), lambda n, v: seen.append((n, v)))
    c.set_val("osd_heartbeat_grace", 33.0)
    assert seen == [("osd_heartbeat_grace", 33.0)]


def test_config_argv_and_diff():
    c = Config()
    rest = c.parse_argv(["--conf-mon-lease=9.5", "positional", "--conf-log-level", "4"])
    assert rest == ["positional"]
    assert c.get("mon_lease") == 9.5
    d = c.diff()
    assert d["mon_lease"] == 9.5 and d["log_level"] == 4
    assert "osd_pool_default_size" not in d


def test_config_schema_types_validate_defaults():
    for name, opt in SCHEMA.items():
        opt.validate(opt.default)


# -- perf counters ----------------------------------------------------------


def test_perf_counters_dump():
    pc = PerfCounters("osd")
    pc.add_u64_counter("op_w")
    pc.add_u64_gauge("numpg")
    pc.add_time_avg("op_w_latency")
    pc.add_histogram("op_size")
    pc.inc("op_w", 3)
    pc.set("numpg", 8)
    pc.tinc("op_w_latency", 0.5)
    pc.tinc("op_w_latency", 1.5)
    pc.hinc("op_size", 4096)
    d = pc.dump()
    assert d["op_w"] == 3 and d["numpg"] == 8
    assert d["op_w_latency"]["avgcount"] == 2
    assert d["op_w_latency"]["avgtime"] == 1.0
    assert d["op_size"]["count"] == 1
    assert sum(d["op_size"]["buckets"]) == 1


# -- throttle ---------------------------------------------------------------


def test_throttle_blocks_until_put():
    t = Throttle("test", 10)
    assert t.get(8)
    assert not t.get_or_fail(5)
    released = []

    def waiter():
        t.get(5)
        released.append(True)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.05)
    assert not released
    t.put(8)
    th.join(timeout=2)
    assert released
    t.put(5)
    # oversized single request passes an empty throttle
    assert t.get(100, timeout=1)


# -- sharded work queue -----------------------------------------------------


def test_sharded_wq_orders_per_token():
    wq = ShardedWorkQueue("t", 4, process=lambda item: item())
    wq.start()
    results = {i: [] for i in range(8)}

    def make(tok, i):
        def run():
            time.sleep(0.001)
            results[tok].append(i)
        return run

    for i in range(20):
        for tok in range(8):
            wq.queue(tok, make(tok, i))
    assert wq.drain(timeout=10)
    wq.stop()
    for tok in range(8):
        assert results[tok] == list(range(20))


def test_sharded_wq_priority():
    order = []
    claimed = threading.Event()
    gate = threading.Event()

    def process(item):
        if item == "blocker":
            claimed.set()
            gate.wait(5)
        else:
            order.append(item)

    wq = ShardedWorkQueue("t", 1, process=process)
    wq.start()
    wq.queue("x", "blocker", priority=63)
    assert claimed.wait(5)  # worker is busy; the rest queue up behind it
    wq.queue("x", "low", priority=1)
    wq.queue("x", "high", priority=63)
    wq.queue("x", "mid", priority=10)
    gate.set()
    assert wq.drain(timeout=5)
    wq.stop()
    assert order == ["high", "mid", "low"]


# -- heartbeat map ----------------------------------------------------------


def test_heartbeat_map_flags_stalled_worker():
    suicides = []
    hm = HeartbeatMap(on_suicide=suicides.append)
    h = hm.add_worker("w", grace=0.05, suicide_grace=0.1)
    assert hm.is_healthy()
    time.sleep(0.12)
    assert "w" in hm.unhealthy_workers()
    assert suicides == ["w"]
    h.touch()
    assert hm.is_healthy()


# -- context + admin socket -------------------------------------------------


def test_context_admin_socket(tmp_path):
    sock = str(tmp_path / "asok")
    ctx = Context("osd.0", {"admin_socket": sock})
    try:
        pc = ctx.perf.create("osd")
        pc.add_u64_counter("ops")
        pc.inc("ops", 5)
        out = admin_command(sock, "perf dump")
        assert out["osd"]["ops"] == 5
        admin_command(sock, "config set", key="mon_lease", value=7.0)
        out = admin_command(sock, "config get", key="mon_lease")
        assert out["mon_lease"] == 7.0
        assert "config diff" in admin_command(sock, "help")
        ctx.log.log("osd", 1, "hello-admin")
        assert any("hello-admin" in line
                   for line in admin_command(sock, "log dump"))
        assert admin_command(sock, "health")["healthy"]
    finally:
        ctx.shutdown()


def test_log_ring_and_crash_dump():
    import io

    log = Log(default_level=1, ring_size=10, name="osd.1",
              stream=io.StringIO())
    for i in range(20):
        log.log("osd", 10, f"quiet-{i}")  # gathered, not emitted
    recent = log.dump_recent()
    assert len(recent) == 10 and "quiet-19" in recent[-1]
    try:
        raise RuntimeError("boom")
    except RuntimeError as e:
        text = log.dump_on_crash(e)
    assert "boom" in text and "quiet-19" in text


def test_lru_cache_generation_refuses_stale_fills():
    from ceph_tpu.core.lru import LRUCache

    c = LRUCache(capacity=2)
    gen = c.generation()
    assert c.put("a", 1, gen=gen)
    c.clear()  # wholesale invalidation bumps the generation
    assert not c.put("b", 2, gen=gen), "stale-generation fill must drop"
    assert "b" not in c
    assert c.put("b", 2, gen=c.generation())
    c.pop("nope")  # single-key invalidation also bumps
    assert not c.put("c", 3, gen=gen)
    # capacity eviction, LRU order
    g = c.generation()
    c.put("x", 1, gen=g); c.put("y", 2, gen=g)
    c.get("x")
    c.put("z", 3, gen=g)
    assert "y" not in c and "x" in c and "z" in c


def test_osd_bench_admin_command(tmp_path):
    """`ceph daemon osd.N bench` role (reference OSD::bench): raw
    objectstore write throughput over the admin socket."""
    from ceph_tpu.core.admin_socket import admin_command
    from ceph_tpu.core.context import Context
    from ceph_tpu.ec import codec_from_profile
    from ceph_tpu.osd.daemon import OSDService
    from ceph_tpu.store.memstore import MemStore

    sock = str(tmp_path / "osd.asok")
    ctx = Context("osd.7", {"admin_socket": sock})
    svc = OSDService(ctx, 7, MemStore(), None, codec_from_profile)
    svc.store.mkfs()
    svc.init()
    try:
        out = admin_command(sock, "osd.7 bench",
                            count=1 << 20, bsize=1 << 16)
        assert out["bytes_written"] == 1 << 20
        assert out["blocksize"] == 1 << 16
        assert out["bytes_per_sec"] > 0
        assert "osd.7 bench" in admin_command(sock, "help")
    finally:
        svc.shutdown()
        if ctx.admin is not None:
            ctx.admin.stop()
