"""RGW HTTP frontend end-to-end: a SigV4-signing client speaks real
HTTP to a real listening socket backed by a real mini-cluster
(reference: rgw_asio_frontend.cc + the S3 REST surface of
rgw_rest_s3.cc; auth completion rgw_rest_s3.cc:938)."""

import json

import pytest

from ceph_tpu.rgw.frontend import RGWFrontend, SigV4Session


@pytest.fixture(scope="module")
def stack():
    from ceph_tpu.vstart import VStartCluster

    with VStartCluster(n_mons=1, n_osds=3) as c:
        pool = c.create_pool("rgw", size=2)
        io_ = c.client().ioctx(pool)
        fe = RGWFrontend(io_).start()
        user = fe.users.user_create("alice", "Alice")
        sess = SigV4Session(fe.addr, user["access_key"],
                            user["secret_key"])
        yield fe, sess, user
        fe.stop()


def test_bucket_lifecycle_over_http(stack):
    fe, s, _ = stack
    assert s.request("PUT", "/mybucket")[0] == 200
    code, _, body = s.request("GET", "/")
    assert code == 200 and b"<Name>mybucket</Name>" in body
    # duplicate create is a clean 409
    assert s.request("PUT", "/mybucket")[0] == 409


def test_object_roundtrip_over_http(stack):
    fe, s, _ = stack
    s.request("PUT", "/data")
    payload = b"hello over real http" * 100
    code, hdrs, _ = s.request("PUT", "/data/greeting.txt", body=payload,
                              headers={"x-amz-meta-color": "blue"})
    assert code == 200 and hdrs.get("ETag")
    code, hdrs, body = s.request("GET", "/data/greeting.txt")
    assert code == 200 and body == payload
    assert hdrs.get("x-amz-meta-color") == "blue"
    code, hdrs, _ = s.request("HEAD", "/data/greeting.txt")
    assert code == 200 and int(hdrs["Content-Length"]) == len(payload)
    # listing
    code, _, body = s.request("GET", "/data", query="prefix=greet")
    assert code == 200 and b"greeting.txt" in body
    # delete -> 404 afterwards
    assert s.request("DELETE", "/data/greeting.txt")[0] == 204
    assert s.request("GET", "/data/greeting.txt")[0] == 404


def test_multipart_over_http(stack):
    fe, s, _ = stack
    s.request("PUT", "/mp")
    code, _, body = s.request("POST", "/mp/big.bin", query="uploads")
    assert code == 200
    upload_id = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0]
    uid = upload_id.decode()
    p1, p2 = b"A" * 70000, b"B" * 30000
    assert s.request("PUT", "/mp/big.bin", body=p1,
                     query=f"partNumber=1&uploadId={uid}")[0] == 200
    assert s.request("PUT", "/mp/big.bin", body=p2,
                     query=f"partNumber=2&uploadId={uid}")[0] == 200
    code, _, body = s.request("POST", "/mp/big.bin",
                              query=f"uploadId={uid}")
    assert code == 200 and b"-2" in body  # N-part etag
    code, _, body = s.request("GET", "/mp/big.bin")
    assert code == 200 and body == p1 + p2


def test_auth_rejections(stack):
    fe, s, user = stack
    # wrong secret -> SignatureDoesNotMatch
    bad = SigV4Session(fe.addr, user["access_key"], "wrong-secret")
    code, _, body = bad.request("GET", "/")
    assert code == 403 and b"SignatureDoesNotMatch" in body
    # unknown access key
    ghost = SigV4Session(fe.addr, "AKDEADBEEF", "nope")
    assert ghost.request("GET", "/")[0] == 403
    # no auth header at all
    import http.client

    conn = http.client.HTTPConnection(*fe.addr, timeout=10)
    try:
        conn.request("GET", "/")
        assert conn.getresponse().status == 403
    finally:
        conn.close()
    # suspended user
    fe.users.user_suspend(user["uid"])
    try:
        assert s.request("GET", "/")[0] == 403
    finally:
        fe.users.user_suspend(user["uid"], False)
    assert s.request("GET", "/")[0] == 200


def test_tampered_payload_rejected(stack):
    """The content hash is part of the signature: a body that doesn't
    match x-amz-content-sha256 must be rejected."""
    import hashlib
    import http.client
    import time as _t

    fe, s, user = stack
    s.request("PUT", "/tamper")
    # sign for one body, send another (simulating in-flight tampering)
    body_signed = b"genuine"
    body_sent = b"tampered"
    amz_date = _t.strftime("%Y%m%dT%H%M%SZ", _t.gmtime())
    from ceph_tpu.rgw import frontend as fr

    payload_hash = hashlib.sha256(body_signed).hexdigest()
    host = f"{fe.addr[0]}:{fe.addr[1]}"
    hdrs = {"host": host, "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date}
    signed = ";".join(sorted(hdrs))
    canonical = fr._canonical_request("PUT", "/tamper/x", "", hdrs,
                                      signed, payload_hash)
    scope = f"{amz_date[:8]}/{fr.REGION}/s3/aws4_request"
    sts = fr._string_to_sign(amz_date, scope, canonical)
    import hmac as _hmac

    key = fr._derive_key(user["secret_key"], amz_date[:8], fr.REGION, "s3")
    sig = _hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    hdrs["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={user['access_key']}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    conn = http.client.HTTPConnection(*fe.addr, timeout=10)
    try:
        conn.request("PUT", "/tamper/x", body=body_sent, headers=hdrs)
        assert conn.getresponse().status == 400
    finally:
        conn.close()


def test_swift_api_over_http(stack):
    """Swift dialect on the same endpoint (reference rgw_rest_swift):
    tempauth handshake, container + object verbs, JSON listings."""
    import http.client

    fe, s, user = stack

    def req(method, path, body=b"", headers=None):
        conn = http.client.HTTPConnection(*fe.addr, timeout=15)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            r = conn.getresponse()
            return r.status, dict(r.getheaders()), r.read()
        finally:
            conn.close()

    # tempauth: bad creds refused, good creds yield a token
    code, _, _ = req("GET", "/auth/v1.0",
                     headers={"X-Auth-User": user["access_key"],
                              "X-Auth-Key": "wrong"})
    assert code == 403
    code, hdrs, _ = req("GET", "/auth/v1.0",
                        headers={"X-Auth-User": user["access_key"],
                                 "X-Auth-Key": user["secret_key"]})
    assert code == 204 and hdrs.get("X-Auth-Token", "").startswith("AUTH_")
    tok = {"X-Auth-Token": hdrs["X-Auth-Token"]}

    # tokenless requests are 401
    assert req("GET", "/swift/v1")[0] == 401

    assert req("PUT", "/swift/v1/cont", headers=tok)[0] == 201
    assert req("PUT", "/swift/v1/cont", headers=tok)[0] == 202  # idempotent
    payload = b"swift object payload" * 50
    code, hdrs2, _ = req("PUT", "/swift/v1/cont/obj1", body=payload,
                         headers={**tok, "X-Object-Meta-Color": "teal"})
    assert code == 201
    code, hdrs3, body = req("GET", "/swift/v1/cont/obj1", headers=tok)
    assert code == 200 and body == payload
    assert hdrs3.get("X-Object-Meta-Color") == "teal"
    # json container listing
    code, _, body = req("GET", "/swift/v1/cont?format=json", headers=tok)
    assert code == 200
    import json as _json

    rows = _json.loads(body)
    assert rows[0]["name"] == "obj1" and rows[0]["bytes"] == len(payload)
    # account listing shows the container
    code, _, body = req("GET", "/swift/v1", headers=tok)
    assert code == 200 and b"cont" in body
    # teardown semantics
    assert req("DELETE", "/swift/v1/cont", headers=tok)[0] == 409  # not empty
    assert req("DELETE", "/swift/v1/cont/obj1", headers=tok)[0] == 204
    assert req("DELETE", "/swift/v1/cont", headers=tok)[0] == 204
    assert req("GET", "/swift/v1/cont/obj1", headers=tok)[0] == 404
