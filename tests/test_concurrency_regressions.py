"""Targeted regressions for the cross-thread races the PR-18
``unguarded-shared-state`` check surfaced and this round fixed.

Each test pins the FIXED behavior, not the bug: the structural
pattern (torn multi-read of shared state, lock-free boot-time
mutation) is also permanently gated by the static check itself in
tests/test_lint.py, so these are the behavioral half of the contract.
"""

import threading
import time
from types import SimpleNamespace

from ceph_tpu.client.objecter import Objecter
from ceph_tpu.mon.monitor import STATE_LEADER, Monitor


# -- Objecter._calc_target: torn osdmap double-read --------------------------

class _TaggedMap:
    """An osdmap stub that DETECTS tearing: pg_to_up_acting refuses a
    pgid computed by a different epoch's map."""

    def __init__(self, tag: str, primary: int) -> None:
        self.tag = tag
        self.primary = primary

    def object_to_pg(self, pool, oid):
        return (self.tag, pool, oid)

    def pg_to_up_acting(self, pgid):
        assert pgid[0] == self.tag, (
            f"torn read: pgid from map {pgid[0]!r} resolved against "
            f"map {self.tag!r} — _calc_target must snapshot self.osdmap "
            "ONCE (pgid from epoch N, primary from epoch N+1 is the bug)")
        return ([self.primary], self.primary, [self.primary], self.primary)


def test_calc_target_uses_one_map_snapshot():
    obj = object.__new__(Objecter)
    m1, m2 = _TaggedMap("e1", 1), _TaggedMap("e2", 2)
    obj.osdmap = m1
    stop = threading.Event()

    def flip():
        while not stop.is_set():
            obj.osdmap = m2
            obj.osdmap = m1

    th = threading.Thread(target=flip, daemon=True)
    th.start()
    try:
        for _ in range(5000):
            pgid, primary = obj._calc_target(3, "oid")
            # the pair must be coherent with a SINGLE map
            assert (pgid[0], primary) in (("e1", 1), ("e2", 2))
    finally:
        stop.set()
        th.join()


# -- Monitor lease: pn/version/value snapshot --------------------------------

class _Conf:
    def __init__(self, vals):
        self._v = vals

    def get(self, key):
        return self._v[key]


class _KV:
    """paxos_values table keyed by stringified version."""

    def __init__(self):
        self.vals = {"0": b"v0"}

    def get(self, table, key):
        assert table == "paxos_values"
        return self.vals.get(key)


def _lease_mon(captured):
    mon = object.__new__(Monitor)
    mon.ctx = SimpleNamespace(conf=_Conf({
        "mon_tick_interval": 0.0005,
        "mon_lease": 1.0,
        "mon_osd_down_out_interval": 600.0,
    }))
    mon._stop = threading.Event()
    mon.lock = threading.RLock()
    mon.state = STATE_LEADER
    mon._catchup_want = 0
    mon.rank = 0
    mon.accepted_pn = 1
    mon.last_committed = 0
    mon.kv = _KV()
    mon.osdmap = None  # _osd_tick returns early (under the lock)
    mon.services = {"health": SimpleNamespace(tick=lambda: None)}
    mon._peers = lambda: [1]
    mon._send_mon = lambda rank, msg: captured.append(
        (msg.version, bytes(msg.value)))
    mon._log = lambda *a, **kw: None
    return mon


def test_leader_lease_is_coherent_under_concurrent_commits():
    """The lease message's (version, value) pair must come from ONE
    lock hold: the old code read last_committed for the header and
    again for the kv fetch, so a commit landing between the two sent
    a lease whose value belonged to a different version than its
    header claimed."""
    captured = []
    mon = _lease_mon(captured)
    ticker = threading.Thread(target=mon._tick_loop, daemon=True)
    ticker.start()

    stop = threading.Event()

    def commit_loop():
        while not stop.is_set():
            with mon.lock:
                ver = mon.last_committed + 1
                mon.kv.vals[str(ver)] = f"v{ver}".encode()
                mon.last_committed = ver

    bumper = threading.Thread(target=commit_loop, daemon=True)
    bumper.start()
    try:
        deadline = time.monotonic() + 10.0
        while len(captured) < 50 and time.monotonic() < deadline:
            time.sleep(0.005)
    finally:
        stop.set()
        mon._stop.set()
        bumper.join()
        ticker.join(timeout=5)
    assert len(captured) >= 50, "leader never ticked enough leases"
    for ver, value in captured:
        assert value == f"v{ver}".encode(), (
            f"torn lease: header says version {ver} but payload is "
            f"{value!r} — snapshot pn/version/value under one hold")


# -- PG boot-time loads hold the pg lock -------------------------------------

def _probe_store(real, pg, calls):
    class Probe:
        def __getattr__(self, name):
            attr = getattr(real, name)
            if not callable(attr):
                return attr

            def wrapped(*a, **kw):
                calls.append((name, pg.lock._is_owned()))
                return attr(*a, **kw)
            return wrapped
    return Probe()


def test_pg_boot_loads_hold_the_pg_lock():
    """load_from_store()/create_onstore() mutate info/log/acting that
    every other lane reads under pg.lock — boot is concurrent with
    the messenger (a peer's query can land mid-load), so the loads
    must hold the lock too."""
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(__file__))
    from test_recovery_pipeline import _stub_pg

    pg, osd = _stub_pg("plugin=isa k=2 m=1 technique=reed_sol_van",
                       acting=[0, 1, 2])
    calls = []
    osd.store = _probe_store(osd.store, pg, calls)
    pg.create_onstore()
    pg.load_from_store()
    assert calls, "probe saw no store traffic during boot load"
    unlocked = [name for name, owned in calls if not owned]
    assert not unlocked, (
        f"store accessed WITHOUT pg.lock during boot load: {unlocked}")
