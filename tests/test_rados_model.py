"""Model-based randomized op testing — the RadosModel/ceph_test_rados
role (reference src/test/osd/RadosModel.h + TestRados.cc, driven by
qa/tasks/rados.py): a randomized op sequence runs against the REAL
cluster through the real client while a trivial in-memory model mirrors
every op; any divergence between cluster state and model is a
consistency bug.  Replicated and EC pools both run the same sequence
shape."""

import random

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.osd import types as t_

from tests.test_osd_cluster import (EC_POOL, REP_POOL, LibClient,
                                    MiniCluster)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


class Model:
    """The in-memory truth: {oid: {data, xattrs, omap}} — plus the
    ACKED-MUTATION LOG that powers the durability oracle.  The model
    only updates after an op returns success, so model state IS acked
    state; `acked` remembers, per granule (data, one xattr key, one
    omap key, existence), WHICH op acked it — on divergence the report
    names the acking op instead of just the symptom."""

    def __init__(self) -> None:
        self.objs = {}
        self.acked = {}   # (oid, kind, name) -> {step, op}
        self.step = -1

    def ensure(self, oid):
        return self.objs.setdefault(
            oid, {"data": b"", "xattrs": {}, "omap": {}})

    def note_ack(self, op: str, oid: str, kind: str,
                 name: str = "") -> None:
        self.acked[(oid, kind, name)] = {"step": self.step, "op": op}

    def note_removed(self, oid: str) -> None:
        for key in [k for k in self.acked if k[0] == oid]:
            del self.acked[key]
        self.acked[(oid, "removed", "")] = {"step": self.step,
                                            "op": "remove"}


def _rollback_events_for(oid):
    """Divergent-rollback events touching `oid` (forensic channel in
    osd/pg.py): the oracle joins a lost granule to the rewind that
    destroyed it."""
    from ceph_tpu.osd.pg import ROLLBACK_EVENTS

    return [e for e in list(ROLLBACK_EVENTS)
            if any(o == oid for o, _v, _op in e["entries"])]


def _oracle_detail(model, oid, kind, name=""):
    """Acked-durability context for one lost granule: the acking op
    and any rollback events that touched the object."""
    rec = model.acked.get((oid, kind, name))
    parts = []
    if rec is not None:
        parts.append(f"ACKED at step {rec['step']} by {rec['op']}")
    else:
        parts.append("no ack recorded for this granule")
    try:
        for e in _rollback_events_for(oid):
            ents = [f"{o}@{v}" for o, v, _op in e["entries"] if o == oid]
            parts.append(f"rolled back on osd.{e['osd']} pg {e['pg']} "
                         f"to {e['target']}: {ents}")
    except Exception:
        pass
    return " [acked-durability oracle: " + "; ".join(parts) + "]"


def _run_model_sequence(io, rng, rounds, oid_space, model_box=None):
    from ceph_tpu.osd.pg import ROLLBACK_EVENTS

    # the rollback ring is process-global and oid namespaces repeat
    # across runs: stale events from an earlier (clean) run must not
    # be attributed to this run's failure provenance
    ROLLBACK_EVENTS.clear()
    model = Model()
    if model_box is not None:
        model_box.append(model)  # caller forensics see the acked log
    ops_run = {k: 0 for k in ("write_full", "write", "append",
                              "truncate", "remove", "setxattr",
                              "omap_set", "omap_rm")}
    for step in range(rounds):
        model.step = step
        oid = f"m{rng.randrange(oid_space)}"
        op = rng.choice(list(ops_run))
        try:
            if op == "write_full":
                data = rng.randbytes(rng.randrange(1, 8192))
                io.write_full(oid, data)
                model.ensure(oid)["data"] = data
                model.note_ack(op, oid, "data")
            elif op == "write":
                ent = model.ensure(oid)
                off = rng.randrange(0, 4096)
                data = rng.randbytes(rng.randrange(1, 2048))
                io.write(oid, data, off=off)
                cur = bytearray(ent["data"])
                if len(cur) < off:
                    cur.extend(b"\0" * (off - len(cur)))
                cur[off:off + len(data)] = data
                ent["data"] = bytes(cur)
                model.note_ack(op, oid, "data")
            elif op == "append":
                ent = model.ensure(oid)
                data = rng.randbytes(rng.randrange(1, 1024))
                io.append(oid, data)
                ent["data"] += data
                model.note_ack(op, oid, "data")
            elif op == "truncate":
                ent = model.ensure(oid)
                size = rng.randrange(0, 4096)
                io.truncate(oid, size)
                cur = ent["data"]
                ent["data"] = (cur[:size] if len(cur) >= size
                               else cur + b"\0" * (size - len(cur)))
                model.note_ack(op, oid, "data")
            elif op == "remove":
                if oid in model.objs:
                    io.remove(oid)
                    del model.objs[oid]
                    model.note_removed(oid)
                else:
                    with pytest.raises(RadosError):
                        io.remove(oid)
            elif op == "setxattr":
                ent = model.ensure(oid)
                k = f"x{rng.randrange(4)}"
                v = rng.randbytes(16)
                io.setxattr(oid, k, v)
                ent["xattrs"][k] = v
                model.note_ack(op, oid, "xattr", k)
            elif op == "omap_set":
                ent = model.ensure(oid)
                kv = {f"k{rng.randrange(8)}": rng.randbytes(12)
                      for _ in range(rng.randrange(1, 4))}
                io.omap_set(oid, kv)
                ent["omap"].update(kv)
                for k in kv:
                    model.note_ack(op, oid, "omap", k)
            elif op == "omap_rm":
                ent = model.objs.get(oid)
                if ent and ent["omap"]:
                    k = rng.choice(sorted(ent["omap"]))
                    io.operate(oid, [t_.OSDOp(t_.OP_OMAP_RM, keys=[k])])
                    del ent["omap"][k]
                    model.acked.pop((oid, "omap", k), None)
                else:
                    continue
            ops_run[op] += 1
        except RadosError as e:  # pragma: no cover - surface with context
            raise AssertionError(
                f"step {step}: {op} on {oid} failed rc={e.rc}") from e

        if step % 50 == 49:
            _verify(io, model)
    _verify(io, model)
    assert sum(ops_run.values()) >= rounds * 0.8  # the mix actually ran
    return ops_run


def _verify(io, model):
    """The acked-durability oracle: cluster state must equal the model
    exactly — and the model holds ONLY client-acked state, so any
    divergence is an acked mutation that was rewound.  Every failure
    message leads with "{oid}: ..." (the forensics hook keys on it)
    and carries the acking op + any rollback events for the object."""
    listed = set(io.list_objects())
    if listed != set(model.objs):
        missing = set(model.objs) - listed
        extra = listed - set(model.objs)
        detail = ""
        if missing:
            oid = sorted(missing)[0]
            detail = _oracle_detail(model, oid, "data")
        elif extra:
            detail = _oracle_detail(model, sorted(extra)[0], "removed")
        raise AssertionError(
            f"object set diverged: extra={extra} missing={missing}"
            f"{detail}")
    for oid, ent in model.objs.items():
        # ALWAYS read: an object the model says is empty must read
        # empty — skipping the read would hide a lost truncate
        try:
            got = io.read(oid)
        except RadosError as e:
            raise AssertionError(f"{oid}: read failed rc={e.rc}")
        want = ent["data"]
        # trailing zeros are representation-equivalent (sparse tails)
        assert got.rstrip(b"\0") == want.rstrip(b"\0"), (
            f"{oid}: data diverged ({len(got)}B vs {len(want)}B)"
            + _oracle_detail(model, oid, "data"))
        # ghost checks run even when the model holds NOTHING: an acked
        # removal of the last xattr/omap key followed by a rollback
        # resurrecting it is exactly the loss class the oracle exists
        # for (the model's x0..x3/k0..k7 namespaces keep internal
        # attrs like snapset out of the comparison)
        stored = {k: v for k, v in io.getxattrs(oid).items()
                  if k.startswith("x")}
        for k, v in ent["xattrs"].items():
            assert stored.get(k) == v, (
                f"{oid}: xattr {k}"
                + _oracle_detail(model, oid, "xattr", k))
        ghost = set(stored) - set(ent["xattrs"])
        assert not ghost, (
            f"{oid}: unacked xattrs resurrected: {sorted(ghost)}"
            + _oracle_detail(model, oid, "xattr", sorted(ghost)[0]))
        stored = io.omap_get(oid)
        for k, v in ent["omap"].items():
            assert stored.get(k) == v, (
                f"{oid}: omap {k}"
                + _oracle_detail(model, oid, "omap", k))
        ghost = set(stored) - set(ent["omap"])
        assert not ghost, (
            f"{oid}: unacked omap keys resurrected: "
            f"{sorted(ghost)}"
            + _oracle_detail(model, oid, "omap", sorted(ghost)[0]))


def test_rados_model_replicated(cluster, client):
    rng = random.Random(0xC3F)
    ops = _run_model_sequence(client.rc.ioctx(REP_POOL), rng,
                              rounds=300, oid_space=24)
    assert ops["remove"] > 0 and ops["write"] > 0


def test_rados_model_ec(cluster, client):
    """The same randomized consistency sweep over the EC pool: every
    op lands through the RMW/striped-shard write pipeline."""
    rng = random.Random(0xEC)
    ops = _run_model_sequence(client.rc.ioctx(EC_POOL), rng,
                              rounds=200, oid_space=16)
    assert ops["truncate"] > 0 and ops["append"] > 0


def test_rados_model_under_thrash():
    """The model sequence with an OSD thrasher bouncing daemons the
    whole time (qa/tasks/thrashosds.py + rados.py combined): every op
    either completes or retries to completion, and the full-state
    verification still holds at every checkpoint.  This hunt caught
    two real bugs when first run: PGLS omitting known-but-unrecovered
    objects, and a freshly-remapped primary serving ops BEFORE peering
    converged on the authoritative log (now gated with EAGAIN)."""
    import threading
    import time

    from tests.test_osd_cluster import N_OSDS

    c = MiniCluster()
    cl = LibClient(c)
    stop = threading.Event()

    def thrasher():
        rng = random.Random(99)
        while not stop.is_set():
            victim = rng.randrange(N_OSDS)
            try:
                c.kill(victim)
                time.sleep(rng.uniform(0.3, 0.8))
                c.revive(victim)
                time.sleep(rng.uniform(0.5, 1.0))
            except Exception:
                pass

    th = threading.Thread(target=thrasher, daemon=True)
    th.start()
    try:
        ops = _run_model_sequence(cl.rc.ioctx(REP_POOL),
                                  random.Random(0xBEEF),
                                  rounds=250, oid_space=20)
        assert sum(ops.values()) >= 200
    finally:
        stop.set()
        th.join(timeout=10)
        cl.shutdown()
        c.shutdown()


def _dump_thrash_forensics(c, err, seed, model=None):
    """PR-4 caveat follow-up: the EC thrash model flaked ONCE at seed
    0x1EC with a byte mismatch and left nothing to analyze.  On any
    model divergence, capture the failing seed plus a full shard dump
    (per-osd chunk lengths/crcs/_av stamps, pg state/missing/log
    heads) into scratch/ BEFORE the cluster is torn down, so the next
    occurrence is a root-cause session instead of a shrug."""
    import json
    import os
    import time as _time

    from ceph_tpu.core.crc import crc32c
    from ceph_tpu.osd import types as ot
    from ceph_tpu.store.objectstore import Collection, GHObject

    from ceph_tpu.tpu.queue import default_queue

    # staging-pool state rides every forensics dump (PR 6): a
    # divergence with slots still held or host touches recorded
    # implicates the device-resident path's buffer lifecycle, one
    # without them exonerates it
    _dq = default_queue()
    report = {"seed": hex(seed), "time": _time.time(), "error": str(err),
              "osds_up": {i: o.up for i, o in c.osds.items()},
              "staging_pool": {
                  "occupancy": _dq.pool.occupancy,
                  "slots": _dq.pool.nslots,
                  "slot_bytes": _dq.pool.slot_bytes,
                  **_dq.stats.snapshot()},
              "pgs": {}, "object": {}}
    # the _verify assertions lead with "{oid}: ..."
    oid = str(err).split(":", 1)[0].strip() or None
    # the acked-mutation log (oracle): which op acked each granule of
    # the diverged object, plus every divergent-rollback event — the
    # PR-7 schema addition that turns a symptom into a provenance
    from ceph_tpu.osd.pg import ROLLBACK_EVENTS

    report["rollback_events"] = list(ROLLBACK_EVENTS)
    # op-observability evidence (PR 8): every OSD's slow-op ring and
    # in-flight op timelines ride the dump — a divergence now shows
    # WHERE the implicated ops spent their time (stage events), not
    # just what state they left behind.  Down OSDs included: a killed
    # daemon's drained history is exactly the kill-window testimony.
    report["slow_ops"] = {}
    report["ops_in_flight"] = {}
    for i, o in c.osds.items():
        trk = getattr(o, "op_tracker", None)
        if trk is None:
            continue
        try:
            report["slow_ops"][f"osd{i}"] = trk.dump_slow()
            report["ops_in_flight"][f"osd{i}"] = trk.dump_in_flight()
        except Exception as e:  # best-effort forensics
            report["slow_ops"][f"osd{i}"] = {"error": repr(e)}
    if model is not None and oid:
        report["acked_mutations"] = {
            f"{kind}:{name}" if name else kind: rec
            for (o, kind, name), rec in sorted(model.acked.items())
            if o == oid}
    for i, o in c.osds.items():
        if not o.up:
            continue
        for pgid, pg in o.pgs.items():
            if pgid[0] != EC_POOL:
                continue
            key = f"osd{i}.pg{pgid[0]}.{pgid[1]:x}"
            try:
                with pg.lock:
                    report["pgs"][key] = {
                        "state": pg.state, "acting": list(pg.acting),
                        "primary": pg.primary,
                        "log_head": str(pg.log.head),
                        "missing": {k: str(v)
                                    for k, v in pg.missing.items()},
                        "stale_peers": sorted(pg.stale_peers),
                    }
            except Exception as e:  # best-effort forensics
                report["pgs"][key] = {"error": repr(e)}
            if not oid:
                continue
            coll = Collection(ot.pgid_str(pgid) + "_head")
            shards = {}
            for s in range(pg.backend.k + pg.backend.m):
                g = GHObject(oid, shard=s)
                try:
                    if not o.store.exists(coll, g):
                        continue
                    data = o.store.read(coll, g)
                    attrs = o.store.getattrs(coll, g)
                    shards[s] = {
                        "len": len(data), "crc": hex(crc32c(data)),
                        "_av": attrs.get("_av", b"").hex(),
                        "hinfo": attrs.get("hinfo", b"").hex(),
                    }
                except Exception as e:
                    shards[s] = {"error": repr(e)}
            if shards:
                en = pg.log.latest_for(oid)
                report["object"][key] = {
                    "shards": shards,
                    "latest_entry": (None if en is None else
                                     f"op={en.op} v={en.version}"),
                }
    out = os.path.join(os.path.dirname(__file__), "..", "scratch",
                       f"thrash_ec_forensics_{seed:#x}.json")
    with open(os.path.abspath(out), "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)


def test_rados_model_ec_under_thrash():
    """The EC-pool model sequence under OSD thrashing: the hunt that
    drove the round's EC consistency fixes (deletion-push guard,
    backfill authority incl. peer missing sets, source-ranked reads
    with _av attr-version metas, retryable watchdog reads, interval-
    token activations).  Seed 0x1EC was a deterministic xattr-loss
    repro before those fixes."""
    import threading
    import time

    from tests.test_osd_cluster import N_OSDS

    c = MiniCluster()
    cl = LibClient(c)
    stop = threading.Event()

    def thrasher():
        rng = random.Random(0x1EC ^ 3)
        while not stop.is_set():
            victim = rng.randrange(N_OSDS)
            try:
                c.kill(victim)
                time.sleep(rng.uniform(0.4, 0.9))
                c.revive(victim)
                time.sleep(rng.uniform(0.6, 1.2))
            except Exception:
                pass

    th = threading.Thread(target=thrasher, daemon=True)
    th.start()
    model_box = []
    try:
        try:
            ops = _run_model_sequence(cl.rc.ioctx(EC_POOL),
                                      random.Random(0x1EC),
                                      rounds=150, oid_space=16,
                                      model_box=model_box)
        except AssertionError as e:
            # capture the shard-level evidence while the cluster is
            # still alive (PR-4's seed byte-mismatch flake left none)
            stop.set()
            th.join(timeout=10)
            _dump_thrash_forensics(
                c, e, seed=0x1EC,
                model=model_box[0] if model_box else None)
            raise
        assert sum(ops.values()) >= 120
    finally:
        stop.set()
        th.join(timeout=10)
        cl.shutdown()
        c.shutdown()
