"""Model-based randomized op testing — the RadosModel/ceph_test_rados
role (reference src/test/osd/RadosModel.h + TestRados.cc, driven by
qa/tasks/rados.py): a randomized op sequence runs against the REAL
cluster through the real client while a trivial in-memory model mirrors
every op; any divergence between cluster state and model is a
consistency bug.  Replicated and EC pools both run the same sequence
shape."""

import random

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.osd import types as t_

from tests.test_osd_cluster import (EC_POOL, REP_POOL, LibClient,
                                    MiniCluster)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    cl = LibClient(cluster)
    yield cl
    cl.shutdown()


class Model:
    """The in-memory truth: {oid: {data, xattrs, omap}}."""

    def __init__(self) -> None:
        self.objs = {}

    def ensure(self, oid):
        return self.objs.setdefault(
            oid, {"data": b"", "xattrs": {}, "omap": {}})


def _run_model_sequence(io, rng, rounds, oid_space):
    model = Model()
    ops_run = {k: 0 for k in ("write_full", "write", "append",
                              "truncate", "remove", "setxattr",
                              "omap_set", "omap_rm")}
    for step in range(rounds):
        oid = f"m{rng.randrange(oid_space)}"
        op = rng.choice(list(ops_run))
        try:
            if op == "write_full":
                data = rng.randbytes(rng.randrange(1, 8192))
                io.write_full(oid, data)
                model.ensure(oid)["data"] = data
            elif op == "write":
                ent = model.ensure(oid)
                off = rng.randrange(0, 4096)
                data = rng.randbytes(rng.randrange(1, 2048))
                io.write(oid, data, off=off)
                cur = bytearray(ent["data"])
                if len(cur) < off:
                    cur.extend(b"\0" * (off - len(cur)))
                cur[off:off + len(data)] = data
                ent["data"] = bytes(cur)
            elif op == "append":
                ent = model.ensure(oid)
                data = rng.randbytes(rng.randrange(1, 1024))
                io.append(oid, data)
                ent["data"] += data
            elif op == "truncate":
                ent = model.ensure(oid)
                size = rng.randrange(0, 4096)
                io.truncate(oid, size)
                cur = ent["data"]
                ent["data"] = (cur[:size] if len(cur) >= size
                               else cur + b"\0" * (size - len(cur)))
            elif op == "remove":
                if oid in model.objs:
                    io.remove(oid)
                    del model.objs[oid]
                else:
                    with pytest.raises(RadosError):
                        io.remove(oid)
            elif op == "setxattr":
                ent = model.ensure(oid)
                k = f"x{rng.randrange(4)}"
                v = rng.randbytes(16)
                io.setxattr(oid, k, v)
                ent["xattrs"][k] = v
            elif op == "omap_set":
                ent = model.ensure(oid)
                kv = {f"k{rng.randrange(8)}": rng.randbytes(12)
                      for _ in range(rng.randrange(1, 4))}
                io.omap_set(oid, kv)
                ent["omap"].update(kv)
            elif op == "omap_rm":
                ent = model.objs.get(oid)
                if ent and ent["omap"]:
                    k = rng.choice(sorted(ent["omap"]))
                    io.operate(oid, [t_.OSDOp(t_.OP_OMAP_RM, keys=[k])])
                    del ent["omap"][k]
                else:
                    continue
            ops_run[op] += 1
        except RadosError as e:  # pragma: no cover - surface with context
            raise AssertionError(
                f"step {step}: {op} on {oid} failed rc={e.rc}") from e

        if step % 50 == 49:
            _verify(io, model)
    _verify(io, model)
    assert sum(ops_run.values()) >= rounds * 0.8  # the mix actually ran
    return ops_run


def _verify(io, model):
    """Cluster state must equal the model exactly."""
    listed = set(io.list_objects())
    assert listed == set(model.objs), (
        f"object set diverged: extra={listed - set(model.objs)} "
        f"missing={set(model.objs) - listed}")
    for oid, ent in model.objs.items():
        # ALWAYS read: an object the model says is empty must read
        # empty — skipping the read would hide a lost truncate
        try:
            got = io.read(oid)
        except RadosError as e:
            raise AssertionError(f"{oid}: read failed rc={e.rc}")
        want = ent["data"]
        # trailing zeros are representation-equivalent (sparse tails)
        assert got.rstrip(b"\0") == want.rstrip(b"\0"), (
            f"{oid}: data diverged ({len(got)}B vs {len(want)}B)")
        for k, v in ent["xattrs"].items():
            assert io.getxattr(oid, k) == v, f"{oid}: xattr {k}"
        if ent["omap"]:
            assert io.omap_get(oid) == ent["omap"], f"{oid}: omap"


def test_rados_model_replicated(cluster, client):
    rng = random.Random(0xC3F)
    ops = _run_model_sequence(client.rc.ioctx(REP_POOL), rng,
                              rounds=300, oid_space=24)
    assert ops["remove"] > 0 and ops["write"] > 0


def test_rados_model_ec(cluster, client):
    """The same randomized consistency sweep over the EC pool: every
    op lands through the RMW/striped-shard write pipeline."""
    rng = random.Random(0xEC)
    ops = _run_model_sequence(client.rc.ioctx(EC_POOL), rng,
                              rounds=200, oid_space=16)
    assert ops["truncate"] > 0 and ops["append"] > 0


def test_rados_model_under_thrash():
    """The model sequence with an OSD thrasher bouncing daemons the
    whole time (qa/tasks/thrashosds.py + rados.py combined): every op
    either completes or retries to completion, and the full-state
    verification still holds at every checkpoint.  This hunt caught
    two real bugs when first run: PGLS omitting known-but-unrecovered
    objects, and a freshly-remapped primary serving ops BEFORE peering
    converged on the authoritative log (now gated with EAGAIN)."""
    import threading
    import time

    from tests.test_osd_cluster import N_OSDS

    c = MiniCluster()
    cl = LibClient(c)
    stop = threading.Event()

    def thrasher():
        rng = random.Random(99)
        while not stop.is_set():
            victim = rng.randrange(N_OSDS)
            try:
                c.kill(victim)
                time.sleep(rng.uniform(0.3, 0.8))
                c.revive(victim)
                time.sleep(rng.uniform(0.5, 1.0))
            except Exception:
                pass

    th = threading.Thread(target=thrasher, daemon=True)
    th.start()
    try:
        ops = _run_model_sequence(cl.rc.ioctx(REP_POOL),
                                  random.Random(0xBEEF),
                                  rounds=250, oid_space=20)
        assert sum(ops.values()) >= 200
    finally:
        stop.set()
        th.join(timeout=10)
        cl.shutdown()
        c.shutdown()


def _dump_thrash_forensics(c, err, seed):
    """PR-4 caveat follow-up: the EC thrash model flaked ONCE at seed
    0x1EC with a byte mismatch and left nothing to analyze.  On any
    model divergence, capture the failing seed plus a full shard dump
    (per-osd chunk lengths/crcs/_av stamps, pg state/missing/log
    heads) into scratch/ BEFORE the cluster is torn down, so the next
    occurrence is a root-cause session instead of a shrug."""
    import json
    import os
    import time as _time

    from ceph_tpu.core.crc import crc32c
    from ceph_tpu.osd import types as ot
    from ceph_tpu.store.objectstore import Collection, GHObject

    from ceph_tpu.tpu.queue import default_queue

    # staging-pool state rides every forensics dump (PR 6): a
    # divergence with slots still held or host touches recorded
    # implicates the device-resident path's buffer lifecycle, one
    # without them exonerates it
    _dq = default_queue()
    report = {"seed": hex(seed), "time": _time.time(), "error": str(err),
              "osds_up": {i: o.up for i, o in c.osds.items()},
              "staging_pool": {
                  "occupancy": _dq.pool.occupancy,
                  "slots": _dq.pool.nslots,
                  "slot_bytes": _dq.pool.slot_bytes,
                  **_dq.stats.snapshot()},
              "pgs": {}, "object": {}}
    # the _verify assertions lead with "{oid}: ..."
    oid = str(err).split(":", 1)[0].strip() or None
    for i, o in c.osds.items():
        if not o.up:
            continue
        for pgid, pg in o.pgs.items():
            if pgid[0] != EC_POOL:
                continue
            key = f"osd{i}.pg{pgid[0]}.{pgid[1]:x}"
            try:
                with pg.lock:
                    report["pgs"][key] = {
                        "state": pg.state, "acting": list(pg.acting),
                        "primary": pg.primary,
                        "log_head": str(pg.log.head),
                        "missing": {k: str(v)
                                    for k, v in pg.missing.items()},
                        "stale_peers": sorted(pg.stale_peers),
                    }
            except Exception as e:  # best-effort forensics
                report["pgs"][key] = {"error": repr(e)}
            if not oid:
                continue
            coll = Collection(ot.pgid_str(pgid) + "_head")
            shards = {}
            for s in range(pg.backend.k + pg.backend.m):
                g = GHObject(oid, shard=s)
                try:
                    if not o.store.exists(coll, g):
                        continue
                    data = o.store.read(coll, g)
                    attrs = o.store.getattrs(coll, g)
                    shards[s] = {
                        "len": len(data), "crc": hex(crc32c(data)),
                        "_av": attrs.get("_av", b"").hex(),
                        "hinfo": attrs.get("hinfo", b"").hex(),
                    }
                except Exception as e:
                    shards[s] = {"error": repr(e)}
            if shards:
                en = pg.log.latest_for(oid)
                report["object"][key] = {
                    "shards": shards,
                    "latest_entry": (None if en is None else
                                     f"op={en.op} v={en.version}"),
                }
    out = os.path.join(os.path.dirname(__file__), "..", "scratch",
                       f"thrash_ec_forensics_{seed:#x}.json")
    with open(os.path.abspath(out), "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)


def test_rados_model_ec_under_thrash():
    """The EC-pool model sequence under OSD thrashing: the hunt that
    drove the round's EC consistency fixes (deletion-push guard,
    backfill authority incl. peer missing sets, source-ranked reads
    with _av attr-version metas, retryable watchdog reads, interval-
    token activations).  Seed 0x1EC was a deterministic xattr-loss
    repro before those fixes."""
    import threading
    import time

    from tests.test_osd_cluster import N_OSDS

    c = MiniCluster()
    cl = LibClient(c)
    stop = threading.Event()

    def thrasher():
        rng = random.Random(0x1EC ^ 3)
        while not stop.is_set():
            victim = rng.randrange(N_OSDS)
            try:
                c.kill(victim)
                time.sleep(rng.uniform(0.4, 0.9))
                c.revive(victim)
                time.sleep(rng.uniform(0.6, 1.2))
            except Exception:
                pass

    th = threading.Thread(target=thrasher, daemon=True)
    th.start()
    try:
        try:
            ops = _run_model_sequence(cl.rc.ioctx(EC_POOL),
                                      random.Random(0x1EC),
                                      rounds=150, oid_space=16)
        except AssertionError as e:
            # capture the shard-level evidence while the cluster is
            # still alive (PR-4's seed byte-mismatch flake left none)
            stop.set()
            th.join(timeout=10)
            _dump_thrash_forensics(c, e, seed=0x1EC)
            raise
        assert sum(ops.values()) >= 120
    finally:
        stop.set()
        th.join(timeout=10)
        cl.shutdown()
        c.shutdown()
