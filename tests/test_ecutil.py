"""StripeInfo offset-algebra tests (reference src/osd/ECUtil.h:27-71 —
the stripe_info_t invariants every EC consumer leans on).
"""

import numpy as np
import pytest

from ceph_tpu.osd.ecutil import StripeInfo


@pytest.fixture
def si():
    return StripeInfo(k=4, chunk_size=1024)  # stripe_width 4096


def test_stripe_bounds(si):
    assert si.stripe_width == 4096
    assert si.logical_to_prev_stripe_offset(0) == 0
    assert si.logical_to_prev_stripe_offset(4095) == 0
    assert si.logical_to_prev_stripe_offset(4096) == 4096
    assert si.logical_to_next_stripe_offset(1) == 4096
    assert si.logical_to_next_stripe_offset(4096) == 4096
    off, length = si.offset_len_to_stripe_bounds(5000, 100)
    assert (off, length) == (4096, 4096)
    off, length = si.offset_len_to_stripe_bounds(4000, 200)
    assert (off, length) == (0, 8192)


def test_chunk_offsets(si):
    assert si.logical_to_prev_chunk_offset(8191) == 1024
    assert si.logical_to_next_chunk_offset(8193) == 3072
    assert si.aligned_logical_offset_to_chunk_offset(8192) == 2048
    assert si.aligned_chunk_offset_to_logical_offset(2048) == 8192
    with pytest.raises(AssertionError):
        si.aligned_logical_offset_to_chunk_offset(100)
    # the two are inverses on aligned values
    for off in (0, 4096, 40960):
        assert si.aligned_chunk_offset_to_logical_offset(
            si.aligned_logical_offset_to_chunk_offset(off)) == off


def test_stripe_range_and_extent(si):
    assert si.stripe_range(0, 1) == (0, 1)
    assert si.stripe_range(4095, 2) == (0, 2)
    assert si.stripe_range(8192, 4096) == (2, 3)
    assert si.stripe_range(100, 0) == (0, 0)
    assert si.chunk_extent(2, 5) == (2048, 3072)
    assert si.object_stripes(0) == 1
    assert si.object_stripes(4097) == 2


def test_interleave_roundtrip(si):
    rng = np.random.default_rng(0)
    for size in (1, 4096, 5000, 65536):
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        planes, S = si.interleave(data)
        assert planes.shape == (4, S * 1024)
        assert si.deinterleave(planes, size) == data


def test_interleave_placement_matches_layout_contract(si):
    """Logical bytes [s*width + j*unit, ...) live at chunk offset s*unit
    of shard j — the documented stripe layout."""
    data = bytes(range(256)) * 32  # 8192 bytes = 2 stripes
    planes, S = si.interleave(data)
    assert S == 2
    for s in range(2):
        for j in range(4):
            logical = data[s * 4096 + j * 1024: s * 4096 + (j + 1) * 1024]
            assert planes[j, s * 1024: (s + 1) * 1024].tobytes() == logical
