"""RGW round-5 feature surface: ACLs, object versioning, lifecycle
(reference src/rgw/rgw_acl_s3.cc, rgw_rados versioning paths,
src/rgw/rgw_lc.cc) — exercised over real HTTP with two SigV4 users
plus direct gateway calls for the scanner clock."""

import json
import time

import pytest

from ceph_tpu.rgw import acl as acl_mod
from ceph_tpu.rgw.frontend import RGWFrontend, SigV4Session


@pytest.fixture(scope="module")
def stack():
    from ceph_tpu.vstart import VStartCluster

    with VStartCluster(n_mons=1, n_osds=3) as c:
        pool = c.create_pool("rgw", size=2)
        io_ = c.client().ioctx(pool)
        fe = RGWFrontend(io_).start()
        alice = fe.users.user_create("alice", "Alice")
        bob = fe.users.user_create("bob", "Bob")
        sa = SigV4Session(fe.addr, alice["access_key"],
                          alice["secret_key"])
        sb = SigV4Session(fe.addr, bob["access_key"],
                          bob["secret_key"])
        yield fe, sa, sb
        fe.stop()


# ---------------------------------------------------------------------------
# ACL model unit surface
# ---------------------------------------------------------------------------

def test_acl_model():
    a = acl_mod.canned_acl("alice", "public-read")
    assert acl_mod.allows(a, "alice", "FULL_CONTROL")
    assert acl_mod.allows(a, "bob", "READ")
    assert acl_mod.allows(a, None, "READ")  # anonymous via AllUsers
    assert not acl_mod.allows(a, "bob", "WRITE")
    auth = acl_mod.canned_acl("alice", "authenticated-read")
    assert acl_mod.allows(auth, "bob", "READ")
    assert not acl_mod.allows(auth, None, "READ")
    # xml round trip
    back = acl_mod.from_xml(acl_mod.to_xml(a).encode())
    assert back == a


def test_acl_xml_rejects_garbage():
    with pytest.raises(acl_mod.InvalidAcl):
        acl_mod.from_xml(b"<wat/>")
    with pytest.raises(acl_mod.InvalidAcl):
        acl_mod.validate({"owner": "a",
                          "grants": [{"grantee": "b", "perm": "FLY"}]})


# ---------------------------------------------------------------------------
# Cross-user denial over HTTP
# ---------------------------------------------------------------------------

def test_cross_user_denied(stack):
    fe, sa, sb = stack
    assert sa.request("PUT", "/private-b")[0] == 200
    assert sa.request("PUT", "/private-b/secret.txt",
                      body=b"top secret")[0] == 200
    # bob can neither list, read, nor write
    assert sb.request("GET", "/private-b")[0] == 403
    assert sb.request("GET", "/private-b/secret.txt")[0] == 403
    assert sb.request("PUT", "/private-b/mine.txt", body=b"x")[0] == 403
    assert sb.request("DELETE", "/private-b/secret.txt")[0] == 403
    # owner still has it all
    assert sa.request("GET", "/private-b/secret.txt")[2] == b"top secret"
    # bob cannot delete or re-ACL the bucket either
    assert sb.request("DELETE", "/private-b")[0] == 403
    assert sb.request("PUT", "/private-b", query="acl")[0] == 403


def test_public_read_and_grant(stack):
    fe, sa, sb = stack
    sa.request("PUT", "/pub-b")
    sa.request("PUT", "/pub-b/hello", body=b"world",
               headers={"x-amz-acl": "public-read"})
    # bob can read the public object but not write over it
    code, _, body = sb.request("GET", "/pub-b/hello")
    assert (code, body) == (200, b"world")
    assert sb.request("PUT", "/pub-b/hello", body=b"nope")[0] == 403
    # grant bob WRITE on the bucket via PUT ?acl XML
    policy = {"owner": "alice",
              "grants": [{"grantee": "bob", "perm": "WRITE"},
                         {"grantee": "bob", "perm": "READ"}]}
    code, _, _ = sa.request("PUT", "/pub-b", query="acl",
                            body=acl_mod.to_xml(policy).encode())
    assert code == 200
    assert sb.request("PUT", "/pub-b/bobs.txt", body=b"hi")[0] == 200
    # GET ?acl shows the grants (owner only by default)
    code, _, body = sa.request("GET", "/pub-b", query="acl")
    assert code == 200 and b"bob" in body
    # bob lacks READ_ACP
    assert sb.request("GET", "/pub-b", query="acl")[0] == 403


# ---------------------------------------------------------------------------
# Versioning
# ---------------------------------------------------------------------------

def _enable_versioning(sess, bucket):
    body = (b"<VersioningConfiguration>"
            b"<Status>Enabled</Status></VersioningConfiguration>")
    code, _, _ = sess.request("PUT", f"/{bucket}", query="versioning",
                              body=body)
    assert code == 200


def test_versioning_roundtrip(stack):
    fe, sa, _ = stack
    sa.request("PUT", "/ver-b")
    # pre-versioning object becomes the null version
    sa.request("PUT", "/ver-b/doc", body=b"v0-legacy")
    _enable_versioning(sa, "ver-b")
    code, _, body = sa.request("GET", "/ver-b", query="versioning")
    assert code == 200 and b"Enabled" in body

    code, h1, _ = sa.request("PUT", "/ver-b/doc", body=b"v1")
    v1 = h1["x-amz-version-id"]
    code, h2, _ = sa.request("PUT", "/ver-b/doc", body=b"v2")
    v2 = h2["x-amz-version-id"]
    assert v1 != v2

    # current is v2; explicit versionIds fetch history incl. null
    assert sa.request("GET", "/ver-b/doc")[2] == b"v2"
    assert sa.request("GET", "/ver-b/doc",
                      query=f"versionId={v1}")[2] == b"v1"
    assert sa.request("GET", "/ver-b/doc",
                      query="versionId=null")[2] == b"v0-legacy"

    # list versions: newest first, latest flagged
    code, _, body = sa.request("GET", "/ver-b", query="versions")
    assert code == 200
    assert body.index(v2.encode()) < body.index(v1.encode())
    assert b"<IsLatest>true</IsLatest>" in body

    # delete without versionId -> marker; object 404s; history stays
    code, hd, _ = sa.request("DELETE", "/ver-b/doc")
    assert code == 204 and hd.get("x-amz-delete-marker") == "true"
    marker_vid = hd["x-amz-version-id"]
    assert sa.request("GET", "/ver-b/doc")[0] == 404
    assert sa.request("GET", "/ver-b/doc",
                      query=f"versionId={v2}")[2] == b"v2"

    # removing the marker restores v2 (the S3 "undelete")
    code, _, _ = sa.request("DELETE", "/ver-b/doc",
                            query=f"versionId={marker_vid}")
    assert code == 204
    assert sa.request("GET", "/ver-b/doc")[2] == b"v2"

    # deleting the CURRENT version promotes v1
    sa.request("DELETE", "/ver-b/doc", query=f"versionId={v2}")
    assert sa.request("GET", "/ver-b/doc")[2] == b"v1"

    # versioned bucket with surviving versions refuses deletion
    assert sa.request("DELETE", "/ver-b")[0] == 409


def test_versioning_suspended(stack):
    fe, sa, _ = stack
    sa.request("PUT", "/susp-b")
    _enable_versioning(sa, "susp-b")
    code, h, _ = sa.request("PUT", "/susp-b/k", body=b"kept")
    kept_vid = h["x-amz-version-id"]
    body = (b"<VersioningConfiguration>"
            b"<Status>Suspended</Status></VersioningConfiguration>")
    assert sa.request("PUT", "/susp-b", query="versioning",
                      body=body)[0] == 200
    # suspended writes land as the null version, replaced in place
    code, h1, _ = sa.request("PUT", "/susp-b/k", body=b"null-1")
    assert h1["x-amz-version-id"] == "null"
    sa.request("PUT", "/susp-b/k", body=b"null-2")
    assert sa.request("GET", "/susp-b/k")[2] == b"null-2"
    # the enabled-era version survives
    assert sa.request("GET", "/susp-b/k",
                      query=f"versionId={kept_vid}")[2] == b"kept"
    # only ONE null version exists
    code, _, body = sa.request("GET", "/susp-b", query="versions")
    assert body.count(b"<VersionId>null</VersionId>") == 1


def test_versioned_delete_converges(stack):
    """Multisite-replay safety: deletes on absent keys 404, and a
    second no-versionId delete returns the EXISTING marker instead of
    stacking a new one (deliberate S3 divergence, documented in
    gateway.delete_object)."""
    fe, sa, _ = stack
    sa.request("PUT", "/conv-b")
    _enable_versioning(sa, "conv-b")
    assert sa.request("DELETE", "/conv-b/never-existed")[0] == 404
    sa.request("PUT", "/conv-b/f", body=b"x")
    code, h1, _ = sa.request("DELETE", "/conv-b/f")
    assert h1.get("x-amz-delete-marker") == "true"
    code, h2, _ = sa.request("DELETE", "/conv-b/f")
    assert h2["x-amz-version-id"] == h1["x-amz-version-id"]
    code, _, body = sa.request("GET", "/conv-b", query="versions")
    assert body.count(b"<DeleteMarker>") == 1


def test_versioning_put_malformed_xml(stack):
    fe, sa, _ = stack
    sa.request("PUT", "/badxml-b")
    assert sa.request("PUT", "/badxml-b", query="versioning",
                      body=b"<notxml")[0] == 400
    assert sa.request("PUT", "/badxml-b", query="versioning",
                      body=b"")[0] == 400


def test_versioned_object_acl_patch(stack):
    """PUT ?acl on the current version patches in place (atomic
    ver_update): history order and data survive."""
    fe, sa, sb = stack
    sa.request("PUT", "/vacl-b")
    _enable_versioning(sa, "vacl-b")
    sa.request("PUT", "/vacl-b/f", body=b"v1")
    code, h, _ = sa.request("PUT", "/vacl-b/f", body=b"v2")
    v2 = h["x-amz-version-id"]
    assert sb.request("GET", "/vacl-b/f")[0] == 403
    policy = {"owner": "alice",
              "grants": [{"grantee": "bob", "perm": "READ"}]}
    assert sa.request("PUT", "/vacl-b/f", query="acl",
                      body=acl_mod.to_xml(policy).encode())[0] == 200
    assert sb.request("GET", "/vacl-b/f")[2] == b"v2"
    # history intact: two versions, v2 still latest
    code, _, body = sa.request("GET", "/vacl-b", query="versions")
    assert body.count(b"<Version>") == 2
    assert f"<VersionId>{v2}</VersionId>".encode() in body


def test_acl_owner_takeover_rejected(stack):
    """A WRITE_ACP grantee may edit grants but NOT the Owner — the
    takeover path (policy with a different Owner) is rejected."""
    fe, sa, sb = stack
    sa.request("PUT", "/own-b")
    policy = {"owner": "alice",
              "grants": [{"grantee": "bob", "perm": "WRITE_ACP"},
                         {"grantee": "bob", "perm": "READ"}]}
    assert sa.request("PUT", "/own-b", query="acl",
                      body=acl_mod.to_xml(policy).encode())[0] == 200
    steal = {"owner": "bob", "grants": []}
    code, _, body = sb.request("PUT", "/own-b", query="acl",
                               body=acl_mod.to_xml(steal).encode())
    assert code == 403 and b"owner" in body
    # alice still rules
    assert sa.request("GET", "/own-b", query="acl")[0] == 200


def test_implicit_null_version_visible(stack):
    """Objects that predate versioning are version 'null' the moment
    versioning turns on — readable, listable, deletable by versionId
    with no intervening write."""
    fe, sa, _ = stack
    sa.request("PUT", "/leg-b")
    sa.request("PUT", "/leg-b/old", body=b"pre-versioning")
    _enable_versioning(sa, "leg-b")
    assert sa.request("GET", "/leg-b/old",
                      query="versionId=null")[2] == b"pre-versioning"
    code, _, body = sa.request("GET", "/leg-b", query="versions")
    assert b"<Key>old</Key>" in body and \
        b"<VersionId>null</VersionId>" in body
    assert sa.request("DELETE", "/leg-b/old",
                      query="versionId=null")[0] == 204
    assert sa.request("GET", "/leg-b/old")[0] == 404


def test_multipart_versioned(stack):
    fe, sa, _ = stack
    sa.request("PUT", "/mpv-b")
    _enable_versioning(sa, "mpv-b")
    code, _, body = sa.request("POST", "/mpv-b/big", query="uploads")
    uid = body.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    sa.request("PUT", "/mpv-b/big", body=b"A" * 70000,
               query=f"partNumber=1&uploadId={uid}")
    sa.request("PUT", "/mpv-b/big", body=b"B" * 30000,
               query=f"partNumber=2&uploadId={uid}")
    assert sa.request("POST", "/mpv-b/big",
                      query=f"uploadId={uid}")[0] == 200
    code, h, body = sa.request("GET", "/mpv-b/big")
    assert code == 200 and body == b"A" * 70000 + b"B" * 30000
    assert "x-amz-version-id" in h


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def test_lifecycle_config_roundtrip(stack):
    fe, sa, sb = stack
    sa.request("PUT", "/lc-b")
    lc = (b"<LifecycleConfiguration><Rule><ID>r1</ID>"
          b"<Prefix>tmp/</Prefix><Status>Enabled</Status>"
          b"<Expiration><Days>1</Days></Expiration>"
          b"</Rule></LifecycleConfiguration>")
    assert sa.request("PUT", "/lc-b", query="lifecycle",
                      body=lc)[0] == 200
    code, _, body = sa.request("GET", "/lc-b", query="lifecycle")
    assert code == 200 and b"tmp/" in body and b"<Days>1</Days>" in body
    # non-owner cannot set lifecycle
    assert sb.request("PUT", "/lc-b", query="lifecycle",
                      body=lc)[0] == 403
    # malformed rejected
    assert sa.request("PUT", "/lc-b", query="lifecycle",
                      body=b"<LifecycleConfiguration/>")[0] == 400
    assert sa.request("DELETE", "/lc-b", query="lifecycle")[0] == 204
    assert sa.request("GET", "/lc-b", query="lifecycle")[0] == 404


def test_lifecycle_expiry(stack):
    fe, sa, _ = stack
    rgw = fe.rgw
    sa.request("PUT", "/exp-b")
    sa.request("PUT", "/exp-b/tmp/old", body=b"old")
    sa.request("PUT", "/exp-b/tmp/new", body=b"new")
    sa.request("PUT", "/exp-b/keep/x", body=b"keep")
    rgw.put_lifecycle("exp-b", [{"id": "exp", "prefix": "tmp/",
                                 "expiration_days": 2}])
    # backdate tmp/old via the index (the scanner trusts mtime)
    old = rgw.head_object("exp-b", "tmp/old")
    old["mtime"] = time.time() - 3 * 86400
    rgw.io.call(rgw._index_oid("exp-b"), "rgw", "index_put",
                json.dumps({"key": "tmp/old", "entry": old}).encode())
    stats = rgw.lc_process("exp-b")
    assert stats["expired"] == 1
    assert sa.request("GET", "/exp-b/tmp/old")[0] == 404
    assert sa.request("GET", "/exp-b/tmp/new")[2] == b"new"
    assert sa.request("GET", "/exp-b/keep/x")[2] == b"keep"


def test_lifecycle_noncurrent_expiry(stack):
    fe, sa, _ = stack
    rgw = fe.rgw
    sa.request("PUT", "/ncv-b")
    _enable_versioning(sa, "ncv-b")
    sa.request("PUT", "/ncv-b/f", body=b"gen1")
    sa.request("PUT", "/ncv-b/f", body=b"gen2")
    rgw.put_lifecycle("ncv-b", [{"id": "nc", "prefix": "",
                                 "noncurrent_days": 5}])
    # backdate the noncurrent version inside the olh row
    olh = rgw._olh("ncv-b", "f")
    olh[0]["mtime"] = time.time() - 6 * 86400
    rgw.io.omap_set(rgw._index_oid("ncv-b"),
                    {"~olh/f": json.dumps(olh).encode()})
    stats = rgw.lc_process("ncv-b")
    assert stats["noncurrent_expired"] == 1
    # current survives; old version gone
    assert sa.request("GET", "/ncv-b/f")[2] == b"gen2"
    code, _, body = sa.request("GET", "/ncv-b", query="versions")
    assert body.count(b"<Key>f</Key>") == 1
