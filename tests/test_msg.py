"""Messenger tests: roundtrip, ordering, reply-over-session, reconnect
resend (reference tier: src/test/msgr/ style, localhost sockets).
"""

import threading
import time

import pytest

from ceph_tpu.core.context import Context
from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.msg.message import EntityName, Message, register
from ceph_tpu.msg.messenger import Dispatcher, Messenger


@register
class MEcho(Message):
    TYPE = 9001

    def __init__(self, text: str = "") -> None:
        super().__init__()
        self.text = text

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.text)

    def decode_payload(self, d: Decoder) -> None:
        self.text = d.string()


@register
class MEchoReply(Message):
    TYPE = 9002

    def __init__(self, text: str = "") -> None:
        super().__init__()
        self.text = text

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.text)

    def decode_payload(self, d: Decoder) -> None:
        self.text = d.string()


class Collector(Dispatcher):
    def __init__(self, reply: bool = False) -> None:
        self.got = []
        self.resets = []
        self.reply = reply
        self.cond = threading.Condition()

    def ms_dispatch(self, conn, msg) -> bool:
        with self.cond:
            self.got.append(msg)
            self.cond.notify_all()
        if self.reply and isinstance(msg, MEcho):
            conn.send(MEchoReply(msg.text.upper()))
        return True

    def ms_handle_reset(self, conn) -> None:
        with self.cond:
            self.resets.append(conn)
            self.cond.notify_all()

    def wait_for(self, n: int, timeout: float = 10.0) -> bool:
        with self.cond:
            return self.cond.wait_for(lambda: len(self.got) >= n, timeout)

    def wait_for_text(self, text: str, timeout: float = 10.0) -> bool:
        with self.cond:
            return self.cond.wait_for(
                lambda: any(getattr(m, "text", None) == text
                            for m in self.got),
                timeout,
            )


@pytest.fixture
def ctx():
    return Context("client.1")


def _mk(ctx, name):
    m = Messenger(ctx, EntityName.parse(name))
    m.start()
    return m


def test_message_registry_roundtrip():
    m = MEcho("hello")
    m.tid = 42
    m.src = EntityName("osd", 3)
    m2 = Message.from_bytes(m.to_bytes())
    assert isinstance(m2, MEcho)
    assert m2.text == "hello" and m2.tid == 42
    assert m2.src == EntityName("osd", 3)


def test_send_and_dispatch(ctx):
    a = _mk(ctx, "client.1")
    b = _mk(ctx, "osd.0")
    coll = Collector()
    b.add_dispatcher(coll)
    try:
        for i in range(10):
            a.send_message(MEcho(f"m{i}"), b.addr)
        assert coll.wait_for(10)
        texts = [m.text for m in coll.got]
        assert texts == [f"m{i}" for i in range(10)]  # ordered
        assert coll.got[0].src == EntityName("client", 1)
    finally:
        a.shutdown()
        b.shutdown()


def test_reply_over_same_session(ctx):
    a = _mk(ctx, "client.1")
    b = _mk(ctx, "osd.0")
    server = Collector(reply=True)
    client = Collector()
    b.add_dispatcher(server)
    a.add_dispatcher(client)
    try:
        conn = a.connect(b.addr)
        conn.send(MEcho("ping"))
        assert client.wait_for(1)
        assert isinstance(client.got[0], MEchoReply)
        assert client.got[0].text == "PING"
    finally:
        a.shutdown()
        b.shutdown()


def test_reconnect_resends_unacked(ctx):
    """Lossless-peer: kill the receiver, restart on the same port, and
    unacked messages must be replayed (reference AsyncConnection
    requeue_sent / resend on reconnect)."""
    a = _mk(ctx, "osd.1")
    b = _mk(ctx, "osd.2")
    coll = Collector()
    b.add_dispatcher(coll)
    addr = b.addr
    try:
        a.send_message(MEcho("before"), addr)
        assert coll.wait_for(1)
        b.shutdown()  # peer dies with the session open

        a.send_message(MEcho("while-down"), addr)  # queued + unacked
        time.sleep(0.3)

        b2 = Messenger(ctx, EntityName.parse("osd.2"),
                       bind_ip=addr[0], bind_port=addr[1])
        coll2 = Collector()
        b2.add_dispatcher(coll2)
        b2.start()
        try:
            # both the replayed 'before' (unacked) and the queued
            # 'while-down' must arrive; arrival order is session order
            assert coll2.wait_for_text("while-down", timeout=15)
        finally:
            b2.shutdown()
    finally:
        a.shutdown()


def test_duplicate_suppression_after_replay(ctx):
    """Replayed frames the peer already dispatched must be dropped by
    in_seq (at-most-once dispatch per session seq)."""
    a = _mk(ctx, "osd.1")
    b = _mk(ctx, "osd.2")
    coll = Collector()
    b.add_dispatcher(coll)
    try:
        conn = a.connect(b.addr)
        conn.send(MEcho("x"))
        assert coll.wait_for(1)
        # forge: replay the same seq by resetting out_seq and resending
        # (simulates a retransmit racing an ack)
        conn2 = a.connect(b.addr)
        assert conn2 is conn
        before = len(coll.got)
        m = MEcho("x")

        def resend_same_seq():
            conn.out_seq -= 1  # will reuse the seq just sent
            conn._enqueue(m)

        a._loop.call_soon_threadsafe(resend_same_seq)
        time.sleep(0.5)
        assert len(coll.got) == before  # duplicate dropped
    finally:
        a.shutdown()
        b.shutdown()


class HoldingServer(Dispatcher):
    """Stores the request's connection; replies only when told to."""

    def __init__(self) -> None:
        self.conns = []
        self.event = threading.Event()

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MEcho):
            self.conns.append(conn)
            self.event.set()
            return True
        return False


def test_reply_survives_socket_death(ctx):
    """Lossless in BOTH directions: a reply queued after the socket died
    must be delivered when the dialer reconnects the same session (the
    accepted side persists per-(src,nonce,sid) state and replays)."""
    a = _mk(ctx, "client.9")
    b = _mk(ctx, "osd.9")
    server = HoldingServer()
    client = Collector()
    b.add_dispatcher(server)
    a.add_dispatcher(client)
    try:
        conn = a.connect(b.addr)
        conn.send(MEcho("req"))
        assert server.event.wait(10)
        srv_conn = server.conns[0]

        # sever the socket before any reply is sent
        def kill():
            if conn._writer:
                conn._writer.close()

        a._loop.call_soon_threadsafe(kill)
        time.sleep(0.5)

        # the reply is queued on a session with no live socket ...
        srv_conn.send(MEchoReply("LATE"))
        # ... and must still arrive once the dialer redials
        assert client.wait_for_text("LATE", timeout=15)
    finally:
        a.shutdown()
        b.shutdown()


def test_dup_suppression_across_reconnect(ctx):
    """A replayed frame already dispatched before the session dropped
    must NOT dispatch twice on the new socket (state keyed by src+nonce
    survives socket turnover)."""
    a = _mk(ctx, "osd.3")
    b = _mk(ctx, "osd.4")
    coll = Collector()
    b.add_dispatcher(coll)
    try:
        conn = a.connect(b.addr)
        conn.send(MEcho("only-once"))
        assert coll.wait_for_text("only-once")
        # simulate ack loss + reconnect: reconstruct the original frame
        # and force the dialer to drop + redial with it still unacked
        import struct as _s
        from ceph_tpu.core.crc import crc32c as _crc
        m = MEcho("only-once")
        m.seq = 1
        m.nonce = a.nonce
        m.sid = conn.sid
        m.src = a.entity
        body = m.to_bytes()
        frame = _s.pack("<II", len(body), _crc(body)) + body
        def forge2():
            conn.acked = 0
            conn._unacked = [(1, frame)]
            if conn._writer:
                conn._writer.close()  # triggers reconnect + replay
        a._loop.call_soon_threadsafe(forge2)
        time.sleep(1.0)  # reconnect + replay happens
        assert [m.text for m in coll.got].count("only-once") == 1
    finally:
        a.shutdown()
        b.shutdown()


def test_lossy_client_policy_drops_on_reset(ctx):
    """Policy.lossy_client (src/msg/Policy.h): the session dies with the
    socket — no reconnect, no replay; the dispatcher sees a reset and
    the higher layer owns retries."""
    from ceph_tpu.msg.messenger import Policy

    a = _mk(ctx, "client.7")
    a.set_policy("osd", Policy.lossy_client())
    b = _mk(ctx, "osd.0")
    server = Collector()
    client = Collector()
    b.add_dispatcher(server)
    a.add_dispatcher(client)
    try:
        conn = a.connect(b.addr, peer_type="osd")
        assert conn.policy.lossy
        conn.send(MEcho("before"))
        assert server.wait_for(1)
        port = b.addr[1]
        b.shutdown()
        # sends into the dead session are dropped, not queued for replay
        conn.send(MEcho("lost"))
        deadline = time.monotonic() + 10
        while not conn._closed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert conn._closed, "lossy session must die with the socket"
        assert conn._unacked == []
        assert client.resets, "dispatcher must hear ms_handle_reset"
        # restart the peer on the same port: nothing is replayed
        b2 = Messenger(ctx, EntityName.parse("osd.0"), bind_port=port)
        b2.start()
        server2 = Collector()
        b2.add_dispatcher(server2)
        # a NEW connect works (fresh session through the same API)
        conn2 = a.connect(b.addr, peer_type="osd")
        assert conn2 is not conn
        conn2.send(MEcho("fresh"))
        assert server2.wait_for_text("fresh")
        assert not any(m.text == "lost" for m in server2.got)
        b2.shutdown()
    finally:
        a.shutdown()


def test_stateless_server_policy_forgets_sessions(ctx):
    """Policy.stateless_server: an accepted lossy session is never
    retained for replay across sockets."""
    from ceph_tpu.msg.messenger import Policy

    a = _mk(ctx, "client.9")
    b = _mk(ctx, "osd.3")
    b.set_policy("client", Policy.stateless_server())
    server = Collector(reply=True)
    b.add_dispatcher(server)
    client = Collector()
    a.add_dispatcher(client)
    try:
        conn = a.connect(b.addr)
        conn.send(MEcho("hi"))
        assert client.wait_for(1)  # reply arrived over the same socket
        assert b._accepted_sessions == {}  # nothing retained
    finally:
        a.shutdown()
        b.shutdown()


def test_default_policy_unchanged_lossless(ctx):
    from ceph_tpu.msg.messenger import Policy

    m = _mk(ctx, "osd.5")
    try:
        assert not m.get_policy("anything").lossy
        m.set_default_policy(Policy.lossy_client())
        assert m.get_policy("osd").lossy
        m.set_policy("mon", Policy.lossless_peer())
        assert not m.get_policy("mon").lossy
    finally:
        m.shutdown()
