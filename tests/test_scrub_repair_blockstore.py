"""Scrub repair against AT-REST corruption under BlockStore.

The VERDICT-r3 "done" scenario for scrub repair (reference repair
scrub mode, src/osd/PG.cc:5042 + qa/standalone/scrub/): flip bytes in
the raw block device file behind a live OSD, let BlockStore's
crc32c-at-rest detection surface the damage, scrub -> inconsistent,
repair -> shard reconstructed from peers, re-read clean.
"""

import pytest

from ceph_tpu.osd import types as t_
from ceph_tpu.store.blockstore import BlockStore
from ceph_tpu.store.objectstore import Collection, GHObject

from tests.test_osd_cluster import EC_POOL, N_OSDS, LibClient, MiniCluster


@pytest.fixture(scope="module")
def bcluster(tmp_path_factory):
    base = tmp_path_factory.mktemp("bstores")
    c = MiniCluster(store_factory=lambda i: BlockStore(str(base / f"osd{i}")))
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def bclient(bcluster):
    cl = LibClient(bcluster)
    yield cl
    cl.shutdown()


def _flip_at_rest(store: BlockStore, pattern: bytes) -> None:
    """Byte-flip the on-device copy of `pattern` behind the store."""
    store._dev_fh.flush()
    with open(store._dev_path, "r+b") as f:
        raw = f.read()
        pos = raw.find(pattern)
        assert pos >= 0, "shard bytes not found on device"
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in pattern[:16]))
    # drop caches so reads hit the flipped media
    store._onodes.clear()
    store._blobs.clear()


def test_repair_after_at_rest_byte_flip(bcluster, bclient):
    payload = b"media-rot-survivor" * 800
    bclient.put(EC_POOL, "atrest", payload)
    pgid, acting, primary = bcluster.primary_of(EC_POOL, "atrest")
    pg = bcluster.osds[primary].pgs[pgid]
    assert pg.scrub().get("atrest") is None

    coll = Collection(t_.pgid_str(pgid) + "_head")
    victim_shard = next(s for s, o in enumerate(acting)
                        if o != primary and 0 <= o < N_OSDS)
    victim = acting[victim_shard]
    g = GHObject("atrest", shard=victim_shard)
    good = bcluster.osds[victim].store.read(coll, g)
    _flip_at_rest(bcluster.osds[victim].store, good)

    # the store itself must now refuse the read (crc32c-at-rest)
    with pytest.raises(Exception):
        bcluster.osds[victim].store.read(coll, g)

    errors = pg.scrub()
    assert "atrest" in errors, errors
    post = pg.repair()
    assert post.get("atrest") is None, post
    assert bcluster.osds[victim].store.read(coll, g) == good
    assert bclient.get(EC_POOL, "atrest") == payload
